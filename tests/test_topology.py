"""Topology-aware collectives (ISSUE 9): SHM intra-host lanes, scoped
sub-groups, the hierarchical (two-level) ring, and algorithm autoselection.

Tier-1 on purpose (``topology`` marker, NOT ``slow``): SHM lanes are now
the default intra-host transport of the data plane, so they must be proven
on every PR.  In-process rigs (one DataPlane per 'rank', threads) cover
frame parity and ring numerics; spawned worlds cover the eager sub-group
path and the SHM peer-death chaos e2e.  Simulated host layouts come from
``TPU_DIST_HOST_ID_R{rank}`` (per-rank fingerprint override).
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

pytestmark = [pytest.mark.topology]

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def store():
    from tpu_dist.dist.store import TCPStore
    s = TCPStore(is_master=True)
    yield s
    s.close()


@pytest.fixture
def hosts(monkeypatch):
    """Per-rank host fingerprints for in-process rigs."""
    def set_hosts(mapping):
        for r, h in mapping.items():
            monkeypatch.setenv(f"TPU_DIST_HOST_ID_R{r}", h)
    return set_hosts


def _run_world(store, n, fn, timeout=60):
    from tpu_dist.collectives.transport import DataPlane
    dps = [DataPlane(store, r, n) for r in range(n)]
    out, errs = [None] * n, []

    def run(r):
        try:
            out[r] = fn(dps[r], r)
        except Exception as e:
            errs.append((r, e))

    threads = [threading.Thread(target=run, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    for dp in dps:
        dp.close()
    assert not errs, errs
    return out, dps


# ---------------------------------------------------------------------------
# ShmLane unit
# ---------------------------------------------------------------------------


class TestShmLane:
    def test_roundtrip_and_wraparound(self):
        from tpu_dist.collectives.shm import ShmLane
        tx = ShmLane(create=True, capacity=4096)
        try:
            rx = ShmLane(name=tx.name)
            rng = np.random.default_rng(0)
            for size in (1, 100, 4096, 5000, 3):   # 5000 > capacity: wraps
                payload = rng.integers(0, 256, size, dtype=np.uint8)
                buf = bytearray(size)
                t = threading.Thread(
                    target=tx.write, args=(payload.tobytes(), 30))
                t.start()
                rx.read_into(buf, timeout=30)
                t.join(10)
                assert bytes(buf) == payload.tobytes(), size
            rx.close()
        finally:
            tx.close()

    def test_partial_write_resume_frame_bigger_than_ring(self):
        from tpu_dist.collectives.shm import ShmLane
        tx = ShmLane(create=True, capacity=4096)
        try:
            rx = ShmLane(name=tx.name)
            payload = np.random.default_rng(1).integers(
                0, 256, 1 << 16, dtype=np.uint8).tobytes()  # 16x the ring
            buf = bytearray(len(payload))
            t = threading.Thread(target=tx.write, args=(payload, 30))
            t.start()
            rx.read_into(buf, timeout=30)
            t.join(10)
            assert not t.is_alive()
            assert bytes(buf) == payload
            rx.close()
        finally:
            tx.close()

    def test_read_abort_check_raises_connection_error(self):
        from tpu_dist.collectives.shm import ShmLane
        tx = ShmLane(create=True, capacity=4096)
        try:
            rx = ShmLane(name=tx.name)
            buf = bytearray(64)   # nothing will ever be written
            with pytest.raises(ConnectionError, match="peer died"):
                rx.read_into(buf, timeout=30,
                             abort_check=lambda: "peer died (test)")
            rx.close()
        finally:
            tx.close()

    def test_read_deadline_raises_timeout(self):
        from tpu_dist.collectives.shm import ShmLane
        tx = ShmLane(create=True, capacity=4096)
        try:
            rx = ShmLane(name=tx.name)
            with pytest.raises(TimeoutError):
                rx.read_into(bytearray(8), timeout=0.2)
            rx.close()
        finally:
            tx.close()


# ---------------------------------------------------------------------------
# SHM transport: frame parity with TCP
# ---------------------------------------------------------------------------


class TestShmTransport:
    def _pair(self, store, same_host):
        from tpu_dist.collectives.transport import DataPlane
        dp0 = DataPlane(store, 0, 2)
        dp1 = DataPlane(store, 1, 2)
        return dp0, dp1

    def test_frames_ride_shm_when_colocated(self, store, hosts):
        hosts({0: "hX", 1: "hX"})
        dp0, dp1 = self._pair(store, True)
        try:
            a = np.arange(9001, dtype=np.float32)
            dp0.send_array(1, "t", a)
            got = dp1.recv_array(0, "t", timeout=30)
            np.testing.assert_array_equal(got, a)
            assert dp0.shm_active(1), "co-located pair should use the lane"
        finally:
            dp0.close()
            dp1.close()

    def test_tcp_when_hosts_differ_or_disabled(self, store, hosts,
                                               monkeypatch):
        hosts({0: "hX", 1: "hY"})
        dp0, dp1 = self._pair(store, False)
        try:
            dp0.send_array(1, "t", np.ones(4096, np.float32))
            dp1.recv_array(0, "t", timeout=30)
            assert not dp0.shm_active(1)
        finally:
            dp0.close()
            dp1.close()
        monkeypatch.setenv("TPU_DIST_SHM", "0")
        hosts({2: "hZ", 3: "hZ"})
        from tpu_dist.collectives.transport import DataPlane
        dp2, dp3 = DataPlane(store, 2, 4), DataPlane(store, 3, 4)
        try:
            dp2.send_array(3, "t", np.ones(4096, np.float32))
            dp3.recv_array(2, "t", timeout=30)
            assert not dp2.shm_active(3), "TPU_DIST_SHM=0 must force TCP"
        finally:
            dp2.close()
            dp3.close()

    def test_shm_frame_parity_with_tcp(self, store, hosts, monkeypatch):
        """Every frame shape the TCP path carries — dtypes, 0-d, empty,
        bf16, quant — arrives identically through the lane."""
        import ml_dtypes
        from tpu_dist.collectives import quant as Q
        frames = [np.arange(12, dtype=np.int32).reshape(3, 4),
                  np.linspace(0, 1, 10007, dtype=np.float32),
                  np.ones((2, 3, 2), dtype=ml_dtypes.bfloat16),
                  np.array([], dtype=np.float64),
                  np.array(3.5, dtype=np.float32)]
        sch = Q.QuantScheme(256)
        qpay = np.random.default_rng(3).standard_normal(5003) \
            .astype(np.float32)
        q, s = Q.quantize(qpay, sch)

        def roundtrip(shm_on):
            monkeypatch.setenv("TPU_DIST_SHM", "auto" if shm_on else "0")
            hosts({0: "hS", 1: "hS"})
            dp0, dp1 = self._pair(store, True)
            try:
                out = []
                for i, arr in enumerate(frames):
                    dp0.send_array(1, f"f{i}", arr)
                    out.append(dp1.recv_array(0, f"f{i}", timeout=30))
                dp0.send_quant(1, "q", Q.QuantChunk(q, s, sch))
                chunk = dp1.recv_array(0, "q", timeout=30)
                assert dp0.shm_active(1) == shm_on
                return out, chunk
            finally:
                dp0.close()
                dp1.close()

        shm_out, shm_chunk = roundtrip(True)
        tcp_out, tcp_chunk = roundtrip(False)
        for a, b, src in zip(shm_out, tcp_out, frames):
            assert a.dtype == b.dtype == src.dtype
            assert a.shape == b.shape == src.shape
            np.testing.assert_array_equal(np.asarray(a, np.float64),
                                          np.asarray(b, np.float64))
        np.testing.assert_array_equal(shm_chunk.q, tcp_chunk.q)
        np.testing.assert_array_equal(shm_chunk.scales, tcp_chunk.scales)
        assert shm_chunk.scheme is tcp_chunk.scheme

    def test_partial_write_resume_through_dataplane(self, store, hosts,
                                                    monkeypatch):
        """A frame bigger than the whole ring flows via partial-write
        resume while the receiver drains concurrently."""
        monkeypatch.setenv("TPU_DIST_SHM_RING", "65536")
        hosts({0: "hP", 1: "hP"})
        dp0, dp1 = self._pair(store, True)
        try:
            huge = np.random.default_rng(5).standard_normal(1 << 18) \
                .astype(np.float32)   # 1 MiB >> 64 KiB ring
            t = threading.Thread(
                target=dp0.send_array, args=(1, "h", huge))
            t.start()
            got = dp1.recv_array(0, "h", timeout=60)
            t.join(30)
            assert not t.is_alive()
            np.testing.assert_array_equal(got, huge)
            assert dp0.shm_active(1)
        finally:
            dp0.close()
            dp1.close()


# ---------------------------------------------------------------------------
# sub-groups
# ---------------------------------------------------------------------------


class TestSubGroup:
    def test_membership_and_ids(self):
        from tpu_dist.collectives import topology as T
        a = T.SubGroup((1, 3), parent_rank=1, parent_world=4, instance=0)
        b = T.SubGroup((1, 3), parent_rank=0, parent_world=4, instance=0)
        assert a.rank == 0 and a.num_processes == 2
        assert b.rank is None
        assert a.group_id == b.group_id  # same list, same instance
        with pytest.raises(T.GroupMembershipError, match="not a member"):
            b.require_member()
        # order-divergent lists share the set scope but not the id
        c = T.SubGroup((3, 1), parent_rank=1, parent_world=4, instance=0)
        assert c.set_scope == a.set_scope and c.group_id != a.group_id

    def test_new_group_validation(self):
        from tpu_dist.collectives import topology as T

        class _G:
            rank, num_processes = 0, 4
        with pytest.raises(ValueError, match="duplicate"):
            T.new_group([0, 0], group=_G())
        with pytest.raises(ValueError, match="out of range"):
            T.new_group([0, 7], group=_G())
        g1 = T.new_group([0, 1], group=_G())
        g2 = T.new_group([0, 1], group=_G())
        assert g1.group_id != g2.group_id  # fresh instance per creation

    def test_subgroup_ring_numerics_and_isolation(self, store, hosts):
        """Two disjoint sub-groups run ring collectives CONCURRENTLY over
        one world-4 data plane: results are right and never cross."""
        from tpu_dist.collectives import ring
        from tpu_dist.collectives import topology as T
        hosts({r: "h0" for r in range(4)})
        g_even = [T.SubGroup((0, 2), r, 4, instance=0) for r in range(4)]
        g_odd = [T.SubGroup((1, 3), r, 4, instance=0) for r in range(4)]

        def fn(dp, r):
            grp = (g_even if r % 2 == 0 else g_odd)[r]
            gdp = grp.view(dp)
            x = np.full(7001, float(r + 1), np.float32)
            out = ring.ring_all_reduce(gdp, x, op="sum", tag="iso")
            ag = ring.ring_all_gather(gdp, np.full(11, float(r), np.float32),
                                      tag="isoag")
            return out, ag

        out, _ = _run_world(store, 4, fn)
        np.testing.assert_allclose(out[0][0], np.full(7001, 1.0 + 3.0))
        np.testing.assert_allclose(out[1][0], np.full(7001, 2.0 + 4.0))
        np.testing.assert_array_equal(out[0][0], out[2][0])
        np.testing.assert_array_equal(out[1][0], out[3][0])
        # all-gather blocks land in GROUP-local rank order
        np.testing.assert_array_equal(out[2][1][0], np.full(11, 0.0))
        np.testing.assert_array_equal(out[2][1][1], np.full(11, 2.0))

    def test_subgroup_ring_with_quant_and_bounds(self, store, hosts):
        """comm_dtype quantization and a custom bounds= partition run
        unchanged inside a group (the tentpole's compatibility claim)."""
        from tpu_dist.collectives import ring
        from tpu_dist.collectives import topology as T
        hosts({r: "h0" for r in range(3)})
        groups = [T.SubGroup((0, 2), r, 3, instance=0) for r in range(3)]
        n_el = 10007
        bounds = [(0, 128), (128, n_el)]

        def fn(dp, r):
            if r == 1:
                return None
            gdp = groups[r].view(dp)
            x = np.random.default_rng(10 + r).standard_normal(n_el) \
                .astype(np.float32)
            qr = ring.ring_all_reduce(gdp, x, op="sum",
                                      comm_dtype="int8_block256", tag="q")
            br = ring.ring_all_reduce(gdp, x, op="sum", bounds=bounds,
                                      tag="b")
            rs = ring.ring_reduce_scatter(gdp, x, op="sum", tag="rs")
            return qr, br, rs

        out, _ = _run_world(store, 3, fn)
        ref = sum(np.random.default_rng(10 + r).standard_normal(n_el)
                  .astype(np.float32) for r in (0, 2))
        np.testing.assert_array_equal(out[0][0], out[2][0])  # quant: rank-id
        np.testing.assert_allclose(out[0][0], ref, rtol=0.05, atol=0.6)
        np.testing.assert_allclose(out[0][1], ref, rtol=2e-6, atol=1e-4)
        # reduce-scatter shards: group-local rank 0 owns the first span
        lo, hi = ring.ring_chunk_span(n_el, 2, 0)
        np.testing.assert_array_equal(out[0][2], out[0][1][lo:hi])


# ---------------------------------------------------------------------------
# hierarchical vs flat: bitwise parity
# ---------------------------------------------------------------------------


class TestHierarchical:
    @pytest.mark.parametrize("world,layout", [
        (2, {0: "a", 1: "a"}),
        (3, {0: "a", 1: "a", 2: "b"}),
        (4, {0: "a", 1: "a", 2: "b", 3: "b"}),
    ])
    @pytest.mark.parametrize("op", ["sum", "avg"])
    def test_hier_bitwise_equals_flat(self, store, hosts, world, layout,
                                      op):
        """Host-contiguous layouts: the two-level ring's fold order IS the
        flat ring's, so results are bitwise-identical — sum/avg, uneven
        payloads, every world."""
        from tpu_dist.collectives import ring
        from tpu_dist.collectives import topology as T
        hosts(layout)
        n_el = 10007  # coprime with 2-4: chunking is never even

        def fn(dp, r):
            x = np.random.default_rng(20 + r).standard_normal(n_el) \
                .astype(np.float32)
            h = T.hier_all_reduce(dp, x, op=op, tag="h")
            f = ring.ring_all_reduce(dp, x, op=op, tag="f")
            topo = T.detect_topology(dp)
            return h, f, topo.host_major_order()

        out, _ = _run_world(store, world, fn)
        for r in range(world):
            assert out[r][2] == list(range(world))
            np.testing.assert_array_equal(
                out[r][0], out[r][1],
                err_msg=f"hier != flat bitwise at rank {r}")
        for r in range(1, world):
            np.testing.assert_array_equal(out[0][0], out[r][0])

    def test_hier_bitwise_under_quant_wire(self, store, hosts):
        from tpu_dist.collectives import ring
        from tpu_dist.collectives import topology as T
        hosts({0: "a", 1: "a", 2: "b", 3: "b"})

        def fn(dp, r):
            x = np.random.default_rng(30 + r).standard_normal(8009) \
                .astype(np.float32)
            h = T.hier_all_reduce(dp, x, op="sum",
                                  comm_dtype="int8_block256", tag="hq")
            f = ring.ring_all_reduce(dp, x, op="sum",
                                     comm_dtype="int8_block256", tag="fq")
            return h, f

        out, _ = _run_world(store, 4, fn)
        for r in range(4):
            np.testing.assert_array_equal(out[r][0], out[r][1])

    def test_hier_interleaved_layout_reorders_and_agrees(self, store,
                                                         hosts):
        """Interleaved hosts: the two-level ring re-orders host-major;
        results are deterministic, identical on every rank, and equal to
        the flat ring up to float re-association (documented contract)."""
        from tpu_dist.collectives import topology as T
        hosts({0: "a", 1: "b", 2: "a", 3: "b"})

        def fn(dp, r):
            topo = T.detect_topology(dp)
            x = np.random.default_rng(40 + r).standard_normal(6007) \
                .astype(np.float32)
            return T.hier_all_reduce(dp, x, op="sum", tag="hi"), \
                topo.host_major_order()

        out, _ = _run_world(store, 4, fn)
        assert out[0][1] == [0, 2, 1, 3]
        ref = sum(np.random.default_rng(40 + r).standard_normal(6007)
                  .astype(np.float32) for r in range(4))
        for r in range(4):
            np.testing.assert_array_equal(out[0][0], out[r][0])
        np.testing.assert_allclose(out[0][0], ref, rtol=2e-5, atol=1e-4)


# ---------------------------------------------------------------------------
# algorithm autoselection
# ---------------------------------------------------------------------------


class TestAutoselect:
    def test_env_overrides_and_auto_policy(self, monkeypatch):
        from tpu_dist.collectives import topology as T
        topo = T.Topology(["a", "a", "b", "b"])
        monkeypatch.setenv("TPU_DIST_ALGO_CORES", "8")
        assert T.select_algo(8 << 20, topo=topo) == ("hier", True)
        assert T.select_algo(1024, topo=topo) == ("flat", True)
        # no co-location: nothing hierarchical to do
        flat_topo = T.Topology(["a", "b", "c", "d"])
        assert T.select_algo(8 << 20, topo=flat_topo) == ("flat", True)
        # explicit modes win and keep compression
        monkeypatch.setenv("TPU_DIST_ALGO", "flat")
        assert T.select_algo(8 << 20, topo=topo) == ("flat", True)
        monkeypatch.setenv("TPU_DIST_ALGO", "hier")
        assert T.select_algo(1024, topo=topo) == ("hier", True)
        monkeypatch.setenv("TPU_DIST_ALGO", "bogus")
        with pytest.raises(ValueError, match="TPU_DIST_ALGO"):
            T.select_algo(1024, topo=topo)

    def test_compute_bound_guard_closes_quant_inversion(self, monkeypatch):
        """ranks-per-host > cores (the PR 8 world-4 inversion regime):
        auto falls back to the flat f32 ring — compression suppressed."""
        from tpu_dist.collectives import topology as T
        topo = T.Topology(["a", "a", "a", "a"])   # 4 ranks, one host
        monkeypatch.setenv("TPU_DIST_ALGO_CORES", "2")
        assert T.select_algo(8 << 20, topo=topo) == ("flat", False)
        # at ranks-per-host == cores (PR 8's world-2 regime, where int8
        # measured 2.57x FASTER) compression stays on
        topo2 = T.Topology(["a", "a", "b", "b"])
        assert T.select_algo(8 << 20, topo=topo2) == ("hier", True)

    def test_store_agreed_cores_on_heterogeneous_hosts(self, monkeypatch):
        """The guard's core budget is the fleet MINIMUM of the published
        counts — every rank of a heterogeneous job reaches the identical
        decision (a local cpu_count would mute-deadlock mixed hosts)."""
        from tpu_dist.collectives import topology as T
        monkeypatch.delenv("TPU_DIST_ALGO_CORES", raising=False)
        topo = T.Topology(["a", "a", "b", "b"], [1, 1, 16, 16])
        assert T.select_algo(8 << 20, topo=topo) == ("flat", False)
        roomy = T.Topology(["a", "a", "b", "b"], [16, 16, 16, 16])
        assert T.select_algo(8 << 20, topo=roomy) == ("hier", True)

    def test_host_record_roundtrip_and_legacy(self):
        from tpu_dist.collectives import topology as T

        class _Store:
            def __init__(self):
                self.kv = {}

            def set(self, k, v):
                self.kv[k] = v

        s = _Store()
        T.publish_host_fingerprint(s, 3, 7)
        (raw,) = s.kv.values()
        host, cores = T.parse_host_record(raw)
        assert host == T.host_fingerprint(3) and cores >= 1
        assert T.parse_host_record(b"bare-fingerprint") == \
            ("bare-fingerprint", None)

    def test_algo_counters_record_choices(self):
        from tpu_dist.collectives import topology as T
        T.reset_algo_counters()
        T.record_algo("all_reduce", "hier")
        T.record_algo("all_reduce", "hier")
        T.record_algo("all_reduce", "flat")
        c = T.algo_counters(reset=True)
        assert c == {"all_reduce/hier": 2, "all_reduce/flat": 1}
        assert T.algo_counters() == {}


# ---------------------------------------------------------------------------
# spawned e2e: eager sub-group collectives + SHM peer death
# ---------------------------------------------------------------------------

_WORKER_PRELUDE = textwrap.dedent("""
    import json, os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import importlib
    import numpy as np
    rank = int(os.environ["RANK"]); world = int(os.environ["WORLD_SIZE"])
    from tpu_dist.dist.store import TCPStore
    host, _, port = os.environ["TPU_DIST_STORE_ADDR"].rpartition(":")
    store = TCPStore(host, int(port))
    rdzv = importlib.import_module("tpu_dist.dist.rendezvous")
    rdzv._store = store

    class _Group:
        def __init__(self, rank, num_processes):
            self.rank, self.num_processes = rank, num_processes
    g = _Group(rank, world)
    from tpu_dist import collectives as C
    os.environ["TPU_DIST_DP_THRESHOLD"] = "0"

    def finish(payload):
        with open(sys.argv[1] + f"/result{rank}.json", "w") as f:
            json.dump(payload, f)
        store.close()
        sys.exit(0)
""")

# eager collectives scoped to a sub-group: members reduce among themselves
# while outsiders run a DIFFERENT group — values and key namespaces never
# cross; a non-member touching the group raises the named error
_SUBGROUP_EAGER_WORKER = _WORKER_PRELUDE + textwrap.dedent("""
    import hashlib
    lo = C.new_group([0, 1], group=g)
    hi = C.new_group([2, 3], group=g)
    mine, other = (lo, hi) if rank < 2 else (hi, lo)
    x = np.full(50021, float(rank + 1), np.float32)
    out = C.all_reduce_host(x, group=mine, op="sum")
    expect = (1.0 + 2.0) if rank < 2 else (3.0 + 4.0)
    np.testing.assert_allclose(out, np.full(50021, expect, np.float32))
    ag = C.all_gather_host(np.float32(rank), group=mine)
    base = 0.0 if rank < 2 else 2.0
    np.testing.assert_allclose(ag, np.asarray([base, base + 1], np.float32))
    try:
        C.all_reduce_host(x, group=other, op="sum")
        err = None
    except C.GroupMembershipError as e:
        err = "GroupMembershipError"
    dig = hashlib.sha256(np.ascontiguousarray(out).tobytes()).hexdigest()
    store.barrier(world, tag="done", timeout=60)
    finish({"err": err, "digest": dig})
""")

# chaos: rank 1 dies MID-collective with SHM lanes active (both ranks on
# one simulated host); the survivor must get a named PeerGoneError through
# the lane's liveness probe — not a hang
_SHM_PEER_DEATH_WORKER = _WORKER_PRELUDE + textwrap.dedent("""
    from tpu_dist.collectives import transport
    dp = transport.get_data_plane(store, rank, world)
    assert dp is not None
    x = np.random.default_rng(rank).standard_normal(1 << 20) \\
        .astype(np.float32)   # 4 MiB: many sub-chunk frames per ring step
    if rank == 1:
        # send the FIRST sub-chunk of a ring step, then die: rank 0 has
        # frames owed and an SHM lane mid-stream
        from tpu_dist.collectives import ring
        step = 256 * 1024 // 4
        dp.send_array(0, "har", x[:step])
        assert dp.shm_active(0), "test wants the death on the SHM path"
        os._exit(1)
    from tpu_dist.collectives import ring
    try:
        ring.ring_all_reduce(dp, x, op="sum", tag="h")
        finish({"err": None})
    except transport.PeerGoneError as e:
        finish({"err": "PeerGoneError", "named": "rank 1" in str(e)})
""")


def _spawn_world(tmp_path, source, world, env_extra=None, timeout=180,
                 allow_rc=()):
    from tpu_dist.dist.store import TCPStore
    script = tmp_path / "worker.py"
    script.write_text(source)
    server = TCPStore(is_master=True)
    env = dict(os.environ,
               PYTHONPATH=_REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                              ""),
               JAX_PLATFORMS="cpu",
               TPU_DIST_STORE_ADDR=f"127.0.0.1:{server.port}",
               WORLD_SIZE=str(world), **(env_extra or {}))
    env.pop("TPU_DIST_RESTART_COUNT", None)
    env.pop("TPU_DIST_DP_THRESHOLD", None)
    try:
        procs = [subprocess.Popen(
            [sys.executable, str(script), str(tmp_path)],
            env=dict(env, RANK=str(r)), cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for r in range(world)]
        outs = [p.communicate(timeout=timeout) for p in procs]
        rcs = [p.returncode for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        server.close()
    bad = [r for r, rc in enumerate(rcs) if rc != 0 and r not in allow_rc]
    assert not bad, "\n\n".join(
        f"rank {r} rc={rcs[r]}\nstdout:\n{outs[r][0]}\nstderr:\n{outs[r][1]}"
        for r in bad)
    return [json.loads((tmp_path / f"result{r}.json").read_text())
            if (tmp_path / f"result{r}.json").exists() else None
            for r in range(world)]


@pytest.mark.multiprocess
def test_eager_subgroup_collectives_e2e(tmp_path):
    res = _spawn_world(tmp_path, _SUBGROUP_EAGER_WORKER, 4,
                       env_extra={"TPU_DIST_HOST_ID": "one-box"})
    assert all(r["err"] == "GroupMembershipError" for r in res)
    assert res[0]["digest"] == res[1]["digest"]
    assert res[2]["digest"] == res[3]["digest"]
    assert res[0]["digest"] != res[2]["digest"]


@pytest.mark.multiprocess
@pytest.mark.chaos
def test_shm_peer_death_names_rank_not_hang(tmp_path):
    res = _spawn_world(tmp_path, _SHM_PEER_DEATH_WORKER, 2,
                       env_extra={"TPU_DIST_HOST_ID": "one-box",
                                  "TPU_DIST_DP_TIMEOUT": "60"},
                       timeout=120, allow_rc=(1,))
    assert res[0] == {"err": "PeerGoneError", "named": True}
