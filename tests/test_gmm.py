"""Grouped-matmul kernels (ops/gmm.py) vs numpy per-group references.

Interpret mode on CPU (conftest forces the platform): same kernel code as
the TPU Mosaic path.  The MoE-level integration (dropless dispatch equals
the no-drop capacity function) lives in tests/test_moe.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import importlib

from tpu_dist.ops import grouped_linear, tgmm
from tpu_dist.ops.gmm import gmm

# the module object (``from tpu_dist.ops import gmm`` would resolve to the
# same-named FUNCTION re-exported by the package __init__)
gmm_mod = importlib.import_module("tpu_dist.ops.gmm")

# compile-heavy file: excluded from the fast tier (`pytest -m "not slow"`)
pytestmark = pytest.mark.slow

B = 8  # row-block size for the tiny shapes here


def _case(rng, e=3, d=16, h=24, blocks_per_group=(2, 1, 3), live_rows=None):
    """Sorted block-aligned layout: group g owns blocks_per_group[g]
    row blocks; the last allocated block of each group is half padding."""
    nb_live = sum(blocks_per_group)
    nb = nb_live + 2                       # two dead tail blocks
    m = nb * B
    x = np.zeros((m, d), np.float32)
    bg = []
    row_group = np.full(m, -1)
    r = 0
    for g, nblk in enumerate(blocks_per_group):
        n_rows = nblk * B - B // 2         # ragged: half-block padding
        x[r:r + n_rows] = rng.standard_normal((n_rows, d))
        row_group[r:r + n_rows] = g
        bg += [g] * nblk
        r += nblk * B
    bg += [e - 1] * (nb - nb_live)         # dead tail carries last group
    w = rng.standard_normal((e, d, h)).astype(np.float32)
    bias = rng.standard_normal((e, h)).astype(np.float32)
    return (jnp.asarray(x), jnp.asarray(w), jnp.asarray(bias),
            jnp.asarray(bg, jnp.int32), jnp.int32(nb_live), row_group)


def _ref_out(x, w, bias, row_group):
    out = np.zeros((x.shape[0], w.shape[2]), np.float32)
    for i, g in enumerate(row_group):
        if g >= 0:
            out[i] = np.asarray(x)[i] @ np.asarray(w)[g] + np.asarray(bias)[g]
    return out


def test_gmm_matches_per_group_reference(rng):
    x, w, bias, bg, n_live, row_group = _case(rng)
    out = gmm(x, w, bg, n_live, bias=bias, block_rows=B, block_h=16)
    ref = _ref_out(x, w, bias, row_group)
    # pad rows inside live blocks get bias[g] (harmless — the combine
    # never reads them); compare live rows only, plus dead-tail zeros
    live = row_group >= 0
    np.testing.assert_allclose(np.asarray(out)[live], ref[live],
                               atol=1e-5, rtol=1e-5)
    dead_tail = np.arange(x.shape[0]) >= int(n_live) * B
    np.testing.assert_array_equal(np.asarray(out)[dead_tail], 0.0)


def test_tgmm_matches_per_group_reference(rng):
    x, w, bias, bg, n_live, row_group = _case(rng)
    dy = jnp.asarray(rng.standard_normal((x.shape[0], w.shape[2]))
                     .astype(np.float32))
    # zero the pad rows of dy (the grouped_linear contract)
    dy = dy * jnp.asarray((row_group >= 0)[:, None].astype(np.float32))
    dw, db = tgmm(x, dy, bg, w.shape[0], block_rows=B, block_h=16,
                  with_rowsum=True)
    for g in range(w.shape[0]):
        rows = row_group == g
        np.testing.assert_allclose(
            np.asarray(dw)[g], np.asarray(x)[rows].T @ np.asarray(dy)[rows],
            atol=1e-5, rtol=1e-5, err_msg=f"dw[{g}]")
        np.testing.assert_allclose(
            np.asarray(db)[g], np.asarray(dy)[rows].sum(0),
            atol=1e-5, rtol=1e-5, err_msg=f"db[{g}]")


@pytest.mark.parametrize("wide", [False, True])
def test_grouped_linear_grads(rng, wide):
    """Autodiff through grouped_linear equals the dense per-group
    reference — both tgmm orientations (the d > h transpose trick)."""
    d, h = (24, 16) if wide else (16, 24)
    x, w, bias, bg, n_live, row_group = _case(rng, d=d, h=h)
    present = jnp.asarray(np.bincount(row_group[row_group >= 0],
                                      minlength=w.shape[0]) > 0)
    cot = rng.standard_normal((x.shape[0], h)).astype(np.float32)
    cot[row_group < 0] = 0.0               # combine never reads pad rows
    cot = jnp.asarray(cot)

    def f(x, w, bias):
        return jnp.vdot(grouped_linear(x, w, bias, bg, n_live, present,
                                       B, 16), cot)

    def ref(x, w, bias):
        rg = jnp.asarray(np.maximum(row_group, 0))
        mask = jnp.asarray((row_group >= 0).astype(np.float32))[:, None]
        out = (jnp.einsum("md,mdh->mh", x, w[rg]) + bias[rg]) * mask
        return jnp.vdot(out, cot)

    g = jax.grad(f, argnums=(0, 1, 2))(x, w, bias)
    gr = jax.grad(ref, argnums=(0, 1, 2))(x, w, bias)
    for a, b, name in zip(g, gr, ("dx", "dw", "db")):
        if name == "dx":
            live = row_group >= 0          # pad-row dx is unused by the
            a, b = np.asarray(a)[live], np.asarray(b)[live]  # dispatch VJP
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4, err_msg=name)


def test_gmm_fused_activation(rng):
    """activation= applies on the f32 accumulator in-kernel, equal to the
    composition outside."""
    x, w, bias, bg, n_live, row_group = _case(rng)
    fused = gmm(x, w, bg, n_live, bias=bias, block_rows=B, block_h=16,
                activation=jax.nn.gelu)
    outside = jax.nn.gelu(gmm(x, w, bg, n_live, bias=bias, block_rows=B,
                              block_h=16))
    live = row_group >= 0
    np.testing.assert_allclose(np.asarray(fused)[live],
                               np.asarray(outside)[live], atol=1e-6)


def test_block_autoshrink_preserves_numerics(rng, monkeypatch):
    """_fit_blocks splitting caller blocks (VMEM pressure) must expand the
    block→group map transparently — force it with a tiny budget."""
    x, w, bias, bg, n_live, row_group = _case(rng)
    full = gmm(x, w, bg, n_live, bias=bias, block_rows=B, block_h=16)
    monkeypatch.setattr(gmm_mod, "_VMEM_BUDGET", 16 * 1024)
    shrunk = gmm(x, w, bg, n_live, bias=bias, block_rows=B, block_h=16)
    np.testing.assert_allclose(np.asarray(full), np.asarray(shrunk),
                               atol=1e-6)
