"""utils: metric logger windows + step timer."""

import numpy as np

from tpu_dist.utils import MetricLogger, StepTimer


class TestMetricLogger:
    def test_window_average(self, capsys):
        log = MetricLogger(every=3, fmt="s{step} loss={loss:.2f}")
        out = None
        for i in range(6):
            out = log.push(step=i + 1, loss=float(i))
        # windows: [0,1,2] -> 1.0 at step 3; [3,4,5] -> 4.0 at step 6
        assert out == {"loss": 4.0}
        printed = capsys.readouterr().out
        assert "s3 loss=1.00" in printed and "s6 loss=4.00" in printed

    def test_ratio_pairs(self):
        log = MetricLogger(every=2)
        log.push(step=1, acc=(3, 10))
        out = log.push(step=2, acc=(7, 10))
        assert out == {"acc": 0.5}

    def test_fractional_denominator(self):
        log = MetricLogger(every=1)
        assert log.push(step=1, frac=(0.3, 0.5)) == {"frac": 0.6}
        assert log.push(step=2, z=(1.0, 0.0)) == {"z": 0.0}  # empty window

    def test_incomplete_window_returns_none(self):
        log = MetricLogger(every=5)
        assert log.push(step=1, loss=1.0) is None

    def test_device_scalars(self):
        import jax.numpy as jnp
        log = MetricLogger(every=2)
        log.push(step=1, loss=jnp.asarray(2.0))
        out = log.push(step=2, loss=jnp.asarray(4.0))
        assert out == {"loss": 3.0}


class TestStepTimer:
    def test_warmup_excluded_and_stats(self):
        t = StepTimer(warmup=2)
        import time
        for i in range(6):
            with t:
                time.sleep(0.001)
        assert t.steps == 4
        assert t.mean() > 0
        assert t.percentile(50) <= t.percentile(95) or t.steps < 2
        assert "steps=4" in t.summary()

    def test_empty(self):
        t = StepTimer()
        assert t.mean() == 0.0 and t.percentile(50) == 0.0
