"""utils: metric logger windows + step timer + streaming latency
histogram (the serve/bench percentile engine)."""

import threading

import numpy as np
import pytest

from tpu_dist.utils import LatencyHistogram, MetricLogger, StepTimer


class TestLatencyHistogram:
    def test_percentiles_within_resolution(self):
        # the whole point: p50/p95/p99 without storing samples, within the
        # bucket geometry's relative error of numpy's exact answer
        rng = np.random.default_rng(0)
        samples = rng.lognormal(mean=-3.0, sigma=1.0, size=20_000)
        h = LatencyHistogram(resolution=0.02)
        for s in samples:
            h.observe(s)
        assert h.count == len(samples)
        for p in (50, 95, 99):
            exact = float(np.percentile(samples, p))
            got = h.percentile(p)
            # bucket upper edge: within ~2x resolution relative error
            assert abs(got - exact) / exact < 0.05, (p, got, exact)
        s = h.summary()
        assert s["count"] == len(samples)
        assert abs(s["mean"] - samples.mean()) / samples.mean() < 1e-6
        assert s["max"] == samples.max()

    def test_empty_and_validation(self):
        h = LatencyHistogram()
        assert h.percentile(99) is None
        assert h.summary()["count"] == 0
        with pytest.raises(ValueError):
            h.percentile(0)
        with pytest.raises(ValueError):
            LatencyHistogram(min_value=1.0, max_value=0.5)
        with pytest.raises(ValueError):
            LatencyHistogram(resolution=0)

    def test_clamps_and_extremes(self):
        h = LatencyHistogram(min_value=1e-6, max_value=10.0)
        h.observe(-5.0)          # clamps to 0 -> underflow bucket
        h.observe(1e9)           # overflow bucket
        assert h.count == 2
        assert h.percentile(100) == 1e9   # clamped to the observed max

    def test_merge(self):
        a, b = LatencyHistogram(), LatencyHistogram()
        for v in (0.01, 0.02, 0.03):
            a.observe(v)
        for v in (0.04, 0.05):
            b.observe(v)
        a.merge(b)
        assert a.count == 5
        assert a.summary()["max"] == 0.05
        with pytest.raises(ValueError):
            a.merge(LatencyHistogram(resolution=0.1))

    def test_thread_safety_counts(self):
        h = LatencyHistogram()

        def work():
            for _ in range(2000):
                h.observe(0.001)

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert h.count == 8000


class TestMetricLogger:
    def test_window_average(self, capsys):
        log = MetricLogger(every=3, fmt="s{step} loss={loss:.2f}")
        out = None
        for i in range(6):
            out = log.push(step=i + 1, loss=float(i))
        # windows: [0,1,2] -> 1.0 at step 3; [3,4,5] -> 4.0 at step 6
        assert out == {"loss": 4.0}
        printed = capsys.readouterr().out
        assert "s3 loss=1.00" in printed and "s6 loss=4.00" in printed

    def test_ratio_pairs(self):
        log = MetricLogger(every=2)
        log.push(step=1, acc=(3, 10))
        out = log.push(step=2, acc=(7, 10))
        assert out == {"acc": 0.5}

    def test_fractional_denominator(self):
        log = MetricLogger(every=1)
        assert log.push(step=1, frac=(0.3, 0.5)) == {"frac": 0.6}
        assert log.push(step=2, z=(1.0, 0.0)) == {"z": 0.0}  # empty window

    def test_incomplete_window_returns_none(self):
        log = MetricLogger(every=5)
        assert log.push(step=1, loss=1.0) is None

    def test_device_scalars(self):
        import jax.numpy as jnp
        log = MetricLogger(every=2)
        log.push(step=1, loss=jnp.asarray(2.0))
        out = log.push(step=2, loss=jnp.asarray(4.0))
        assert out == {"loss": 3.0}


class TestStepTimer:
    def test_warmup_excluded_and_stats(self):
        t = StepTimer(warmup=2)
        import time
        for i in range(6):
            with t:
                time.sleep(0.001)
        assert t.steps == 4
        assert t.mean() > 0
        assert t.percentile(50) <= t.percentile(95) or t.steps < 2
        assert "steps=4" in t.summary()

    def test_empty(self):
        t = StepTimer()
        assert t.mean() == 0.0 and t.percentile(50) == 0.0
