"""docs/API.md completeness: every public name is indexed.

The index claims to cover every ``__all__`` across the whole package;
this test makes the claim mechanical, so API additions fail loudly until
documented.  Coverage is by identifier token (not raw substring — a name
appearing only inside a longer identifier or prose word does not count),
over every importable submodule except ``__main__`` scripts, underscore
modules, and the ``tpu_dist.run`` alias.
"""

import importlib
import os
import pkgutil
import re

import pytest

_DOC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "docs", "API.md")


def _modules():
    import tpu_dist

    mods = ["tpu_dist"]
    for info in pkgutil.walk_packages(tpu_dist.__path__, prefix="tpu_dist."):
        parts = info.name.split(".")
        if any(p.startswith("_") for p in parts[1:]):
            continue  # private modules and __main__ scripts (which exec)
        if info.name == "tpu_dist.run":
            continue  # torchrun-style alias: importing is fine, but it is
            # documented as a CLI, not an API module
        mods.append(info.name)
    return mods


@pytest.mark.parametrize("modname", _modules())
def test_every_public_name_is_indexed(modname):
    with open(_DOC) as f:
        tokens = set(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", f.read()))
    mod = importlib.import_module(modname)
    names = getattr(mod, "__all__", [])
    missing = [n for n in names
               if n not in tokens and not n.startswith("__")]
    assert not missing, (f"{modname}.__all__ names missing from "
                         f"docs/API.md: {missing}")
