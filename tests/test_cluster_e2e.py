"""Cluster chaos end-to-end: the ISSUE 16 acceptance runs.

Two multi-node control-plane proofs, real OS processes on the CPU backend:

1. **Store-leader SIGKILL mid-training** — external ``tpu_dist.cluster
   .agent`` processes host the replicated store (node 0 leads, node 1
   follows); a launcher in ``--store_endpoints`` client mode trains through
   a SIGKILL of the leader agent.  The follower wins the election, promotes
   its replica, rewrites the endpoints file at epoch 1, and every client
   re-resolves — training finishes in generation 0 with the restart budget
   untouched.

2. **Two-launcher 8→4→8 elastic run crossing a node boundary** — two
   launchers (4 ranks each) share one replicated store; chaos preempts all
   of node 1's ranks at step 5 (the shrink is a CLUSTER decision: node 1
   drops to zero ranks and idles), then grows back to 8 at step 8.  Each
   destination-world phase must be BITWISE equal to an uninterrupted
   single-launcher run at that world size resumed from the same checkpoint
   tree.

Both runs spawn 8-10 jax processes across multiple generations, so they are
``slow``-marked (nightly tier) to protect the tier-1 wall-clock budget; the
control-plane units they integrate (election, replication lag, at-most-once
failover, waiter re-arm) run tier-1 in test_cluster.py.
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import time

import pytest

from test_chaos_e2e import (_REPO, _ZERO_TRAIN_WORKER, _finals, _gen_losses,
                            _launch_train, _trim_ckpt_tree)

pytestmark = [pytest.mark.cluster, pytest.mark.chaos,
              pytest.mark.multiprocess, pytest.mark.slow]


def _agent_env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # fast failover: leases every 0.2s, leader condemned after 1s of dead
    # probes, follower tails at 20ms — the election lands well inside the
    # client reconnect window (~12s of backed-off attempts)
    env.update({"TPU_DIST_CLUSTER_LEASE_INTERVAL": "0.2",
                "TPU_DIST_CLUSTER_LEASE_TTL": "1.0",
                "TPU_DIST_STORE_REPL_POLL": "0.02",
                "TPU_DIST_STORE_DOWN_AFTER": "1.0"})
    env.update(extra or {})
    return env


def _spawn_agent(node_id, ep, ready, *, lead=False, extra_env=None):
    cmd = [sys.executable, "-m", "tpu_dist.cluster.agent",
           "--node_id", str(node_id), "--endpoints", str(ep),
           "--ready_file", str(ready)]
    if lead:
        cmd.append("--lead")
    proc = subprocess.Popen(cmd, cwd=_REPO, env=_agent_env(extra_env),
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True)
    deadline = time.monotonic() + 30
    while not os.path.exists(ready):
        assert proc.poll() is None, f"agent {node_id} died before ready"
        assert time.monotonic() < deadline, f"agent {node_id} never ready"
        time.sleep(0.05)
    with open(ready) as f:
        return proc, json.load(f)


def _wait_step(path, step, deadline, procs):
    """Block until losses file ``path`` records ``step`` (training reached
    mid-run) — the kill must land while steps are still being taken."""
    while time.monotonic() < deadline:
        for p in procs:
            assert p.poll() is None, "process died before the kill point"
        try:
            with open(path) as f:
                if str(step) in json.load(f):
                    return
        except (OSError, ValueError):
            pass
        time.sleep(0.1)
    raise AssertionError(f"step {step} never appeared in {path}")


def test_store_leader_sigkill_training_rides_failover(tmp_path):
    """ISSUE 16 acceptance: SIGKILL the store-leader agent mid-training.
    The follower node's agent detects the dead leader, wins the
    deterministic election, promotes its replica (endpoints epoch 0 -> 1),
    and the in-flight training run — whose gradients ride the p2p data
    plane while every store client re-resolves the new leader — finishes
    in generation 0 without burning a restart."""
    ep = tmp_path / "ep.json"
    leader, lead_info = _spawn_agent(0, ep, tmp_path / "r0.json", lead=True)
    follower, foll_info = _spawn_agent(1, ep, tmp_path / "r1.json")
    train = None
    try:
        out_dir = tmp_path / "train"
        out_dir.mkdir()
        script = tmp_path / "worker.py"
        script.write_text(_ZERO_TRAIN_WORKER)
        env = _agent_env({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            # EVERY gradient leaf on the p2p data plane: the store must be
            # free of in-flight at-most-once ops during the election window
            # (idempotent ops retry across it; a failed SET/ADD cannot)
            "TPU_DIST_DP_THRESHOLD": "0",
            # no checkpoint barrier lands mid-run either
            "E2E_SAVE_EVERY": "50"})
        env.pop("TPU_DIST_CHAOS", None)
        train = subprocess.Popen(
            [sys.executable, "-m", "tpu_dist.launch", "--nproc_per_node=2",
             "--master_port=0", "--max_restarts=1", "--restart_backoff=0.1",
             "--heartbeat_timeout=10", f"--store_endpoints={ep}",
             str(script), str(out_dir), str(out_dir / "ckpt"), "12"],
            cwd=_REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)

        _wait_step(out_dir / "losses_g0_r0.json", 3,
                   time.monotonic() + 180, [train, leader, follower])
        leader.send_signal(signal.SIGKILL)
        out, err = train.communicate(timeout=300)
        assert train.returncode == 0, f"stdout:\n{out}\nstderr:\n{err}"
        # the failover rode OUTSIDE the restart budget
        assert "relaunching" not in err, err

        fa = _finals(out_dir, nproc=2)
        for rank in (0, 1):
            assert fa[rank]["generation"] == 0, fa[rank]
            assert fa[rank]["start"] == 0, fa[rank]
            assert set(fa[rank]["losses"]) == {str(s) for s in range(12)}
        assert len({f["params_sha256"] for f in fa.values()}) == 1

        # the promoted follower is now the published leader
        with open(ep) as f:
            published = json.load(f)
        assert published["epoch"] == 1, published
        assert published["leader"] == f"127.0.0.1:{foll_info['port']}", \
            (published, foll_info)
        follower.send_signal(signal.SIGTERM)
        agent_out = follower.communicate(timeout=20)[0]
        assert "store-failover-promoted" in agent_out, agent_out
    finally:
        for p in (train, leader, follower):
            if p is not None and p.poll() is None:
                p.kill()
                p.communicate(timeout=20)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_node(node_rank, store_port, ep, script, out_dir, ckpt, n_steps,
                 log_path, chaos):
    env = _agent_env({
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "TPU_DIST_DP_THRESHOLD": "1024",
        "TPU_DIST_CHAOS": chaos,
        "TPU_DIST_PREEMPT_SETTLE": "3",
        "E2E_SAVE_EVERY": "2"})
    log = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, "-m", "tpu_dist.launch", "--nnodes=2",
         f"--node_rank={node_rank}", "--nproc_per_node=4",
         "--master_port=0", f"--store_port={store_port}",
         f"--store_endpoints={ep}", "--store_replica",
         "--elastic_world=4:8", "--restart_backoff=0.1",
         "--elastic_timeout=60",
         str(script), str(out_dir), str(ckpt), str(n_steps)],
        cwd=_REPO, env=env, stdout=log, stderr=subprocess.STDOUT, text=True)


@pytest.mark.zero
@pytest.mark.elastic
def test_two_launcher_elastic_8_4_8_across_node_boundary(tmp_path):
    """ISSUE 16 acceptance: a two-launcher world-8 ZeRO run (4 ranks per
    node) is preempted down to world 4 — ALL of node 1's ranks exit
    PREEMPTED at step 5, so the re-form crosses a node boundary: node 1
    idles at zero ranks while node 0 reshards the world-8 step-4 tree and
    carries the world-4 phase alone.  At step 8 capacity returns and the
    cluster grows back to 8, resharding the world-4 step-8 tree across
    both nodes again.  Both transitions are cluster decisions outside the
    restart budget, and each destination-world phase is bitwise equal to
    an uninterrupted single-launcher run at that world size resumed from
    the same checkpoint tree."""
    script = tmp_path / "worker.py"
    script.write_text(_ZERO_TRAIN_WORKER)
    out_dir = tmp_path / "elastic"
    out_dir.mkdir()
    ckpt = out_dir / "ckpt"
    ep = tmp_path / "ep.json"
    store_port = _free_port()
    chaos = (";".join(f"shrink:rank={r},step=5" for r in range(4, 8))
             + ";grow:rank=0,step=8,world=8")
    logs = [tmp_path / f"launch{n}.log" for n in (0, 1)]
    procs = [_launch_node(n, store_port, ep, script, out_dir, ckpt, 12,
                          logs[n], chaos) for n in (0, 1)]
    try:
        deadline = time.monotonic() + 900
        for p in procs:
            p.wait(timeout=max(1, deadline - time.monotonic()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=20)
    texts = [log.read_text() for log in logs]
    assert procs[0].returncode == 0, f"node0:\n{texts[0]}\nnode1:\n{texts[1]}"
    assert procs[1].returncode == 0, f"node0:\n{texts[0]}\nnode1:\n{texts[1]}"
    both = texts[0] + texts[1]
    # both world changes were cluster re-forms outside the restart budget
    assert "cluster elastic re-form: world 8 -> 4" in both, both
    assert "cluster elastic re-form: world 4 -> 8" in both, both
    assert "restart budget untouched" in both, both
    assert "relaunching" not in both, both
    # the shrink crossed the node boundary: node 1 idled at zero ranks
    assert "node 1 runs 0 rank(s)" in texts[1], texts[1]
    assert "node 0 runs 4 rank(s) from base 0" in texts[0], texts[0]

    fa = _finals(out_dir, nproc=8)
    for rank in range(8):
        assert fa[rank]["generation"] == 2, fa[rank]
        assert fa[rank]["start"] == 9, fa[rank]   # resharded from step 8

    # --- world-4 phase vs an uninterrupted single-launcher world-4 run
    # resumed from the same world-8 step-4 tree
    ckpt_b = tmp_path / "ckpt_fixed4"
    shutil.copytree(ckpt, ckpt_b)
    _trim_ckpt_tree(str(ckpt_b), 4)
    rb, dir_b = _launch_train(
        tmp_path, "fixed4", n_steps=12, worker_src=_ZERO_TRAIN_WORKER,
        nproc=4, ckpt_root=ckpt_b, extra_env={"E2E_SAVE_EVERY": "2"},
        timeout=600)
    assert rb.returncode == 0, f"stdout:\n{rb.stdout}\nstderr:\n{rb.stderr}"
    fb = _finals(dir_b, nproc=4)
    for rank in range(4):
        assert fb[rank]["start"] == 5, fb[rank]   # resharded 8->4 resume
        la, lb = _gen_losses(out_dir, 1, rank), _gen_losses(dir_b, 0, rank)
        for step in range(5, 9):
            assert la[str(step)] == lb[str(step)], \
                f"world-4 phase diverged at step {step} rank {rank}"

    # --- world-8 phase vs an uninterrupted single-launcher world-8 run
    # resumed from the same world-4 step-8 tree, params included
    ckpt_c = tmp_path / "ckpt_fixed8"
    shutil.copytree(ckpt, ckpt_c)
    _trim_ckpt_tree(str(ckpt_c), 8)
    rc, dir_c = _launch_train(
        tmp_path, "fixed8", n_steps=12, worker_src=_ZERO_TRAIN_WORKER,
        nproc=8, ckpt_root=ckpt_c, extra_env={"E2E_SAVE_EVERY": "2"},
        timeout=600)
    assert rc.returncode == 0, f"stdout:\n{rc.stdout}\nstderr:\n{rc.stderr}"
    fc = _finals(dir_c, nproc=8)
    for rank in range(8):
        assert fc[rank]["start"] == 9, fc[rank]   # resharded 4->8 resume
        for step in range(9, 12):
            assert fa[rank]["losses"][str(step)] == \
                fc[rank]["losses"][str(step)], \
                f"world-8 phase diverged at step {step} rank {rank}"
    digests = {f["params_sha256"] for f in (*fa.values(), *fc.values())}
    assert len(digests) == 1, f"parameter divergence: {digests}"
