"""On-device augmentation (DeviceAugment): parity with the host transforms'
resample math, exactness in degenerate configs, and loader integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tpu_dist.dist as dist
from tpu_dist.data import (DataLoader, DeviceAugment, DeviceLoader,
                           SyntheticImageNet, transforms)
from tpu_dist.data.device_augment import bilinear_crop_resize
from tpu_dist.data.transforms import _bilinear_crop_resize_numpy


@pytest.fixture
def pg():
    if dist.is_initialized():
        dist.destroy_process_group()
    pg = dist.init_process_group()
    yield pg
    if dist.is_initialized():
        dist.destroy_process_group()


class TestBilinearParity:
    def test_matches_numpy_resampler_on_identical_boxes(self, rng):
        """The jax resampler IS the host resampler (same half-pixel math):
        identical boxes -> identical pixels."""
        x = rng.uniform(0, 1, (4, 37, 41, 3)).astype(np.float32)
        top = rng.uniform(0, 5, 4).astype(np.float32)
        left = rng.uniform(0, 7, 4).astype(np.float32)
        ch = rng.uniform(20, 30, 4).astype(np.float32)
        cw = rng.uniform(20, 30, 4).astype(np.float32)
        want = _bilinear_crop_resize_numpy(x, top, left, ch, cw, (16, 16))
        got = bilinear_crop_resize(jnp.asarray(x), jnp.asarray(top),
                                   jnp.asarray(left), jnp.asarray(ch),
                                   jnp.asarray(cw), (16, 16))
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                                   atol=1e-5)


class TestDeviceAugment:
    def test_identity_config_equals_host_normalize(self, rng):
        """pad_crop with padding=0 and size==input forces offset 0: the
        device pipeline must reduce to exactly ToFloat+Normalize."""
        x8 = rng.integers(0, 256, (3, 32, 32, 3)).astype(np.uint8)
        aug = DeviceAugment.cifar10(32, padding=0, flip_p=0.0)
        got = np.asarray(aug(jnp.asarray(x8), jax.random.key(0)))
        norm = transforms.Normalize(transforms.CIFAR10_MEAN,
                                    transforms.CIFAR10_STD)
        want = norm(x8.astype(np.float32) / 255.0)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_forced_flip_mirrors(self, rng):
        x8 = rng.integers(0, 256, (2, 8, 8, 3)).astype(np.uint8)
        plain = DeviceAugment.cifar10(8, padding=0, flip_p=0.0)
        flip = DeviceAugment.cifar10(8, padding=0, flip_p=1.0)
        a = np.asarray(plain(jnp.asarray(x8), jax.random.key(1)))
        b = np.asarray(flip(jnp.asarray(x8), jax.random.key(1)))
        np.testing.assert_allclose(b, a[:, :, ::-1, :], rtol=1e-6)

    def test_uint8_and_unit_float_agree(self, rng):
        x8 = rng.integers(0, 256, (2, 24, 24, 3)).astype(np.uint8)
        xf = x8.astype(np.float32) / 255.0
        aug = DeviceAugment.imagenet(16)
        a = np.asarray(aug(jnp.asarray(x8), jax.random.key(7)))
        b = np.asarray(aug(jnp.asarray(xf), jax.random.key(7)))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_resized_crop_shape_determinism_and_key_sensitivity(self, rng):
        x8 = rng.integers(0, 256, (4, 48, 48, 3)).astype(np.uint8)
        aug = DeviceAugment.imagenet(24, dtype=jnp.bfloat16)
        a = aug(jnp.asarray(x8), jax.random.key(3))
        assert a.shape == (4, 24, 24, 3) and a.dtype == jnp.bfloat16
        b = aug(jnp.asarray(x8), jax.random.key(3))
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
        c = aug(jnp.asarray(x8), jax.random.key(4))
        assert not np.array_equal(np.asarray(a, np.float32),
                                  np.asarray(c, np.float32))

    def test_pad_crop_windows_are_real_crops(self, rng):
        """Every pad_crop output (flip off) must equal SOME integer window
        of the zero-padded normalized input."""
        x8 = rng.integers(0, 256, (3, 8, 8, 1)).astype(np.uint8)
        aug = DeviceAugment(8, mode="pad_crop", padding=2, flip_p=0.0,
                            mean=(0.0,), std=(1.0,))
        got = np.asarray(aug(jnp.asarray(x8), jax.random.key(9)))
        padded = np.pad(x8.astype(np.float32) / 255.0,
                        ((0, 0), (2, 2), (2, 2), (0, 0)))
        for i in range(3):
            found = any(
                np.allclose(got[i], padded[i, t:t + 8, l:l + 8], atol=1e-6)
                for t in range(5) for l in range(5))
            assert found, f"image {i}: no integer window matches"


class TestDeviceLoaderAugment:
    def test_end_to_end_raw_bytes_to_augmented_batches(self, pg):
        ds = SyntheticImageNet(train=True, n=32, image_size=32,
                               num_classes=10, transform=None)
        host = DataLoader(ds, batch_size=16, shuffle=True, drop_last=True,
                          to_float=False)
        # raw path: host yields uint8
        x, y = next(iter(host))
        assert x.dtype == np.uint8
        aug = DeviceAugment.imagenet(24)
        dev = DeviceLoader(host, group=pg, augment=aug, augment_seed=5)
        batches = [(np.asarray(x), np.asarray(y)) for x, y in dev]
        assert len(batches) == 2
        assert batches[0][0].shape == (16, 24, 24, 3)
        assert batches[0][0].dtype == np.float32
        # same epoch -> same stream; new epoch -> new augmentation draws
        again = [np.asarray(x) for x, _ in dev]
        np.testing.assert_array_equal(batches[0][0], again[0])
        dev.set_epoch(1)
        ep1 = [np.asarray(x) for x, _ in dev]
        assert not np.array_equal(batches[0][0], ep1[0])


class TestImagenetEval:
    def test_identity_resize_equals_host_centercrop(self, rng):
        """Input short side == resize: the device path must reduce to an
        exact integer center crop + normalize (host-oracle equality)."""
        x8 = rng.integers(0, 256, (3, 256, 256, 3)).astype(np.uint8)
        aug = DeviceAugment.imagenet_eval(224, resize=256)
        got = np.asarray(aug(jnp.asarray(x8), jax.random.key(0)))
        norm = transforms.Normalize(transforms.IMAGENET_MEAN,
                                    transforms.IMAGENET_STD)
        want = norm(x8[:, 16:240, 16:240].astype(np.float32) / 255.0)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_close_to_two_pass_host_pipeline(self, rng):
        """Non-trivial scale: the single-pass device resample tracks the
        host's Resize(256)+CenterCrop(224) two-pass pipeline (they differ
        only by resampling error)."""
        x8 = rng.integers(0, 256, (2, 320, 320, 3)).astype(np.uint8)
        aug = DeviceAugment.imagenet_eval(224, resize=256)
        got = np.asarray(aug(jnp.asarray(x8), jax.random.key(0)))
        host = transforms.Compose([
            transforms.Resize(256),
            transforms.CenterCrop(224),
            transforms.Normalize(transforms.IMAGENET_MEAN,
                                 transforms.IMAGENET_STD),
        ])
        want = host(x8.astype(np.float32) / 255.0)
        # normalized units; resampling-order error stays small
        assert np.abs(got - want).mean() < 0.05
        assert np.abs(got - want).max() < 1.0

    def test_deterministic_ignores_key(self, rng):
        x8 = rng.integers(0, 256, (2, 64, 64, 3)).astype(np.uint8)
        aug = DeviceAugment.imagenet_eval(32, resize=48)
        a = np.asarray(aug(jnp.asarray(x8), jax.random.key(0)))
        b = np.asarray(aug(jnp.asarray(x8), jax.random.key(99)))
        np.testing.assert_array_equal(a, b)
