"""Weight-only int8 quantization (nn/quant.py).

Oracle: per-out-channel symmetric int8 bounds the weight error at
scale/2 per element, so quantized logits must track full-precision
logits closely; the converter must swap topology + params consistently
and leave everything else (embeddings, norms, attention) untouched.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_dist import nn
from tpu_dist.models import TransformerLM


def test_quantlinear_matches_linear_within_int8(rng):
    """Direct numeric check: QuantLinear(q, scale) ≈ Linear(w) with the
    per-out-channel error bound |w - q*scale| <= scale/2."""
    from tpu_dist.nn.quant import _quantize_weight

    lin = nn.Linear(64, 32)
    p = lin.init(jax.random.key(0))
    q, scale = _quantize_weight(p[""]["weight"])
    assert q.dtype == np.int8 and scale.shape == (32,)
    w = np.asarray(p[""]["weight"])
    err = np.abs(w - q.astype(np.float32) * scale)
    # bound is scale/2 at rounding ties; allow f32 arithmetic slack
    assert (err <= scale / 2 * (1 + 1e-5) + 1e-7).all(), err.max()

    qlin = nn.QuantLinear(64, 32)
    qp = {"": {"q_weight": jnp.asarray(q), "scale": jnp.asarray(scale),
               "bias": p[""]["bias"]}}
    x = jnp.asarray(rng.standard_normal((8, 64)).astype(np.float32))
    want = np.asarray(lin.apply(p, x))
    got = np.asarray(qlin.apply(qp, x))
    denom = max(np.abs(want).max(), 1e-6)
    assert np.abs(got - want).max() / denom < 0.02

    # a root-level bare Linear is not swappable (no parent): unchanged
    same, same_p = nn.quantize_linear_weights(lin, p)
    assert not isinstance(same, nn.QuantLinear)
    assert "weight" in same_p[""]


def test_converter_swaps_and_matches(rng):
    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(50, 16)
            self.fc1 = nn.Linear(16, 64)
            self.act = nn.GELU()
            self.fc2 = nn.Linear(64, 50)

        def forward(self, idx):
            h = self.act(self.fc1(self.emb(idx)))
            return self.fc2(h)

    net = Net()
    params = net.init(jax.random.key(0))
    x = jnp.asarray(rng.integers(0, 50, (4, 7)))
    want = np.asarray(net.apply(params, x))

    net, qparams = nn.quantize_linear_weights(net, params)
    assert isinstance(net.fc1, nn.QuantLinear)
    assert isinstance(net.fc2, nn.QuantLinear)
    assert not isinstance(net.emb, nn.QuantLinear)
    assert qparams["fc1"]["q_weight"].dtype == jnp.int8
    assert "weight" not in qparams["fc1"]
    assert qparams["emb"] is params["emb"]  # untouched leaf, same object

    got = np.asarray(net.apply(qparams, x))
    # int8 per-channel: logits track closely relative to their scale
    denom = max(np.abs(want).max(), 1e-6)
    assert np.abs(got - want).max() / denom < 0.02, \
        np.abs(got - want).max()


def test_skip_keeps_full_precision(rng):
    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(8, 8)
            self.b = nn.Linear(8, 8)

        def forward(self, x):
            return self.b(self.a(x))

    net = Net()
    params = net.init(jax.random.key(0))
    net, qp = nn.quantize_linear_weights(net, params, skip=["b"])
    assert isinstance(net.a, nn.QuantLinear)
    assert isinstance(net.b, nn.Linear) and not isinstance(net.b,
                                                           nn.QuantLinear)
    assert "weight" in qp["b"] and "q_weight" in qp["a"]


def test_quantized_lm_generates(rng):
    """The converted model drives the same generate() path; greedy tokens
    from a trained-ish model stay consistent with full precision for a
    short horizon."""
    model = TransformerLM(vocab_size=40, dim=32, depth=2, num_heads=4,
                          max_seq_len=32)
    params = model.init(jax.random.key(0))
    prompt = jnp.asarray(rng.integers(0, 40, (2, 6)))
    full = model.generate(params, prompt, 8)

    model, qparams = nn.quantize_linear_weights(model, params)
    # Sequential-held MLP linears swapped too (paths like block0.mlp.0)
    assert isinstance(model.block0.mlp[0], nn.QuantLinear)
    assert isinstance(model.head, nn.QuantLinear)
    out = model.generate(qparams, prompt, 8)
    assert out.shape == full.shape
    np.testing.assert_array_equal(np.asarray(out[:, :6]),
                                  np.asarray(prompt))


def test_full_quant_topk_logit_agreement(rng):
    """The FULLY quantized serving model — Linears (incl. the LM head),
    attention projections, and the embedding table all int8 — keeps
    greedy/top-k behavior: at every position the fp32 model's argmax is
    inside the quantized model's top-5, and the top-1 agrees at >=90% of
    positions (symmetric per-channel int8 holds logit perturbation well
    under typical logit gaps)."""
    model = TransformerLM(vocab_size=64, dim=32, depth=2, num_heads=4,
                          max_seq_len=32)
    params = model.init(jax.random.key(0))
    toks = jnp.asarray(rng.integers(0, 64, (2, 16)))
    full_logits = np.asarray(model.apply(params, toks))

    model, qp = nn.quantize_linear_weights(model, params, attention=True,
                                           embedding=True)
    assert isinstance(model.tok, nn.QuantEmbedding)
    assert isinstance(model.head, nn.QuantLinear)
    q_logits = np.asarray(model.apply(qp, toks))
    assert q_logits.shape == full_logits.shape

    full_top1 = full_logits.argmax(-1)                       # (B, T)
    q_top5 = np.argsort(-q_logits, axis=-1)[..., :5]
    in_top5 = (q_top5 == full_top1[..., None]).any(-1)
    assert in_top5.all(), f"argmax left top-5 at {np.argwhere(~in_top5)}"
    agree = (q_logits.argmax(-1) == full_top1).mean()
    assert agree >= 0.9, f"top-1 agreement {agree:.2f}"


def test_quant_embedding_matches_rows(rng):
    """QuantEmbedding gathers int8 rows + per-row scales; values track
    the fp table within symmetric-int8 error and dtype follows scale."""
    emb = nn.Embedding(20, 16)
    params = emb.init(jax.random.key(0))
    idx = jnp.asarray(rng.integers(0, 20, (4, 3)))
    want = np.asarray(emb.apply(params, idx))

    class Wrap(nn.Module):
        def __init__(self):
            super().__init__()
            self.e = nn.Embedding(20, 16)

        def forward(self, idx):
            return self.e(idx)

    net = Wrap()
    p = net.init(jax.random.key(0))
    p["e"] = dict(params[""])
    net, qp = nn.quantize_linear_weights(net, p, embedding=True)
    assert isinstance(net.e, nn.QuantEmbedding)
    got = np.asarray(net.apply(qp, idx))
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=np.abs(want).max() / 100)


def test_weight_tied_linear_stays_tied(rng):
    """A Linear registered under two attributes (weight tying) must stay
    ONE module after conversion — both paths resolve to the same
    QuantLinear and the single shared params leaf."""
    class Tied(nn.Module):
        def __init__(self):
            super().__init__()
            shared = nn.Linear(8, 8)
            self.fc = shared
            self.out = shared

        def forward(self, x):
            return self.out(self.fc(x))

    net = Tied()
    params = net.init(jax.random.key(0))
    x = jnp.asarray(rng.standard_normal((3, 8)).astype(np.float32))
    want = np.asarray(net.apply(params, x))
    net, qp = nn.quantize_linear_weights(net, params)
    assert isinstance(net.fc, nn.QuantLinear)
    assert net.fc is net.out           # the tie survives
    got = np.asarray(net.apply(qp, x))  # no KeyError for path 'out'
    denom = max(np.abs(want).max(), 1e-6)
    assert np.abs(got - want).max() / denom < 0.05


def test_attention_quantization(rng):
    """attention=True also swaps MHSA for the int8 subclass; logits track
    full precision and the KV-cache decode path still works."""
    model = TransformerLM(vocab_size=40, dim=64, depth=2, num_heads=4,
                          max_seq_len=32)
    params = model.init(jax.random.key(2))
    x = jnp.asarray(rng.integers(0, 40, (2, 12)))
    want = np.asarray(model.apply(params, x))

    model, qp = nn.quantize_linear_weights(model, params, attention=True)
    assert isinstance(model.block0.attn, nn.QuantMultiheadSelfAttention)
    assert qp["block0.attn"]["qkv_q"].dtype == jnp.int8
    assert "qkv_weight" not in qp["block0.attn"]
    got = np.asarray(model.apply(qp, x))
    denom = max(np.abs(want).max(), 1e-6)
    assert np.abs(got - want).max() / denom < 0.05

    prompt = jnp.asarray(rng.integers(0, 40, (1, 5)))
    out = model.generate(qp, prompt, 6)      # cached decode path
    assert out.shape == (1, 11)

    # idempotent: converting again is a no-op for already-quantized paths
    model2, qp2 = nn.quantize_linear_weights(model, qp, attention=True)
    assert qp2["block0.attn"] is qp["block0.attn"]
