"""FSDP (ZeRO-3 via GSPMD placements): sharded params/opt-state train with
numerics identical to the single-device step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import tpu_dist.dist as dist
from tpu_dist import nn, optim
from tpu_dist.models import TransformerLM
from tpu_dist.parallel import fsdp_shard, fsdp_specs, make_gspmd_train_step

# compile-heavy file: excluded from the fast tier (`pytest -m "not slow"`)
pytestmark = pytest.mark.slow

VOCAB, DIM, T = 33, 64, 16


@pytest.fixture(autouse=True)
def _pg_cleanup():
    yield
    if dist.is_initialized():
        dist.destroy_process_group()


def test_fsdp_specs_shard_largest_divisible_dim(eight_devices):
    dist.init_process_group(backend="cpu")
    mesh = dist.get_default_group().mesh
    tree = {"w": jnp.zeros((48, 8)),        # 48 % 8 == 0 -> shard dim 0
            "tall": jnp.zeros((7, 4096)),   # dim0 indivisible -> dim 1
            "bias": jnp.zeros((4096,)),     # 1-D, large -> sharded
            "tiny": jnp.zeros((64,)),       # < min_size -> replicated
            "odd": jnp.zeros((7, 9))}       # nothing divisible -> replicated
    specs = fsdp_specs(tree, mesh, axis="data", min_size=256)
    assert specs["w"] == P("data", None)
    assert specs["tall"] == P(None, "data")
    assert specs["bias"] == P("data")
    assert specs["tiny"] == P()
    assert specs["odd"] == P()


def test_fsdp_step_matches_single_device(eight_devices):
    dist.init_process_group(backend="cpu")
    pg = dist.get_default_group()
    model = TransformerLM(vocab_size=VOCAB, dim=DIM, depth=2, num_heads=4,
                          max_seq_len=T)
    ce = nn.CrossEntropyLoss()
    loss_fn = lambda lg, y: ce(lg.reshape(-1, VOCAB), y.reshape(-1))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, VOCAB, (16, T)))
    y = jnp.asarray(rng.integers(0, VOCAB, (16, T)))

    params0 = model.init(jax.random.key(0))
    # oracle first: the sharded step donates its inputs
    opt = optim.AdamW(lr=1e-3)

    def objective(p):
        return loss_fn(model.apply(p, x), y)

    loss_ref, grads = jax.value_and_grad(objective)(params0)
    ref_p, _ = opt.update(grads, opt.init(params0), params0)

    params = fsdp_shard(params0, pg.mesh, min_size=256)
    opt_state = fsdp_shard(opt.init(params), pg.mesh, min_size=256)
    # ZeRO-3 placement actually happened: the embedding is sharded 1/8
    emb = params["tok"]["weight"]
    assert emb.sharding.spec != P()
    assert len(emb.sharding.device_set) == 8
    shard_elems = np.prod(emb.sharding.shard_shape(emb.shape))
    assert shard_elems == emb.size // 8
    # Adam moments sharded with their params
    m_emb = opt_state["m"]["tok"]["weight"]
    assert m_emb.sharding.spec == emb.sharding.spec

    step = make_gspmd_train_step(model, loss_fn, opt)
    bsh = NamedSharding(pg.mesh, P("data", None))
    new_p, new_opt, metrics = step(params, opt_state,
                                   jax.device_put(x, bsh),
                                   jax.device_put(y, bsh))
    np.testing.assert_allclose(float(metrics["loss"]), float(loss_ref),
                               rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=2e-5), jax.device_get(new_p),
        ref_p)
    # updated params keep their FSDP placement (no silent re-replication)
    assert new_p["tok"]["weight"].sharding.spec == emb.sharding.spec


def test_fsdp_multi_step_trains(eight_devices):
    """Loss falls over steps with params staying sharded throughout."""
    dist.init_process_group(backend="cpu")
    pg = dist.get_default_group()
    model = TransformerLM(vocab_size=VOCAB, dim=DIM, depth=2, num_heads=4,
                          max_seq_len=T)
    ce = nn.CrossEntropyLoss()
    loss_fn = lambda lg, y: ce(lg.reshape(-1, VOCAB), y.reshape(-1))
    opt = optim.AdamW(lr=3e-3)
    params = fsdp_shard(model.init(jax.random.key(0)), pg.mesh, min_size=256)
    opt_state = fsdp_shard(opt.init(params), pg.mesh, min_size=256)
    step = make_gspmd_train_step(model, loss_fn, opt)

    rng = np.random.default_rng(0)
    x = rng.integers(0, VOCAB, (16, T))
    bsh = NamedSharding(pg.mesh, P("data", None))
    xj = jax.device_put(jnp.asarray(x), bsh)
    yj = jax.device_put(jnp.asarray((x + 1) % VOCAB), bsh)
    first = last = None
    for i in range(15):
        params, opt_state, m = step(params, opt_state, xj, yj)
        if i == 0:
            first = float(m["loss"])
        last = float(m["loss"])
    assert first / last > 2, (first, last)


def test_fsdp_none_leaves_pass_through(eight_devices):
    dist.init_process_group(backend="cpu")
    mesh = dist.get_default_group().mesh
    out = fsdp_shard({"a": jnp.zeros((16, 8)), "b": None}, mesh, min_size=8)
    assert out["b"] is None
    assert out["a"].sharding.spec == P("data", None)


def test_fsdp_composes_with_tp_rules(eight_devices):
    """TP-first-then-FSDP: TP-sharded leaves KEEP their model-axis
    placement and gain the fsdp axis on a free dim (2-D weight sharding,
    the Megatron+ZeRO-3 hybrid); remaining replicated leaves get
    data-sharded — the docstring recipe."""
    from tpu_dist.parallel import TRANSFORMER_TP_RULES, shard_pytree
    dist.init_process_group(backend="cpu", axis_names=("data", "model"),
                            mesh_shape=(2, 4))
    mesh = dist.get_default_group().mesh
    # vocab must divide the 4-wide 'model' axis for the TP rules
    model = TransformerLM(vocab_size=32, dim=32, depth=1, num_heads=4,
                          max_seq_len=T)
    params = shard_pytree(model.init(jax.random.key(0)), mesh,
                          TRANSFORMER_TP_RULES)
    assert params["block0.attn"]["qkv_weight"].sharding.spec == \
        P(None, "model")
    params = fsdp_shard(params, mesh, min_size=128)
    # TP axis survives; the free dim picks up the data axis
    assert params["block0.attn"]["qkv_weight"].sharding.spec == \
        P("data", "model")
    assert params["pos"]["weight"].sharding.spec != P()


def test_3d_dp_fsdp_tp_matches_single_device(eight_devices):
    """Full 3-D mesh (data=2, fsdp=2, model=2): batch over 'data', weights
    2-D-sharded over ('fsdp', 'model') — one GSPMD step == the unsharded
    single-device step."""
    from tpu_dist.parallel import TRANSFORMER_TP_RULES, shard_pytree
    dist.init_process_group(backend="cpu",
                            axis_names=("data", "fsdp", "model"),
                            mesh_shape=(2, 2, 2))
    mesh = dist.get_default_group().mesh
    model = TransformerLM(vocab_size=32, dim=32, depth=1, num_heads=2,
                          max_seq_len=T)
    ce = nn.CrossEntropyLoss()
    loss_fn = lambda lg, y: ce(lg.reshape(-1, 32), y.reshape(-1))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 32, (8, T)))
    y = jnp.asarray(rng.integers(0, 32, (8, T)))
    opt = optim.SGD(lr=0.1)
    params0 = model.init(jax.random.key(0))

    # single-device oracle
    def objective(p):
        return loss_fn(model.apply(p, x), y)

    ref_loss, grads = jax.value_and_grad(objective)(params0)
    ref_p, _ = opt.update(grads, opt.init(params0), params0)

    params = shard_pytree(params0, mesh, TRANSFORMER_TP_RULES)
    params = fsdp_shard(params, mesh, axis="fsdp", min_size=128)
    qkv = params["block0.attn"]["qkv_weight"]
    assert qkv.sharding.spec == P("fsdp", "model")  # 2-D weight sharding
    opt_state = fsdp_shard(opt.init(params), mesh, axis="fsdp",
                           min_size=128)
    step = make_gspmd_train_step(model, loss_fn, opt)
    bsh = NamedSharding(mesh, P("data", None))
    new_p, _, m = step(params, opt_state, jax.device_put(x, bsh),
                       jax.device_put(y, bsh))
    np.testing.assert_allclose(float(m["loss"]), float(ref_loss), rtol=1e-5)
    # updated params keep their 2-D placement and match the oracle
    assert new_p["block0.attn"]["qkv_weight"].sharding.spec == \
        P("fsdp", "model")
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=2e-5),
        jax.device_get(new_p), ref_p)
