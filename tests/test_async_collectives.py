"""Async collective engine (ISSUE 5): Work futures, ordered-engine
semantics, the double-buffered pipelined ring, the gradient bucketer's
bitwise parity with the per-leaf ring, and the overlap benchmark smoke.

In-process halves use the test_ring_collectives wiring (one TCPStore, one
DataPlane per fake rank, each driven by a thread, per-rank ordered engines
keyed by plane); the eager ``async_op`` semantics run in spawned worker
processes because the eager layer's sequence counters and engine are
process-global by design.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

pytestmark = [pytest.mark.collectives, pytest.mark.multiprocess]

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Work / ordered-engine units (no sockets)
# ---------------------------------------------------------------------------

class TestWork:
    def test_fifo_order_and_results(self):
        from tpu_dist.collectives.work import _OrderedExecutor
        eng = _OrderedExecutor()
        order = []

        def body(i):
            order.append(i)
            return i * 10

        works = [eng.submit(lambda i=i: body(i), label=f"w{i}")
                 for i in range(8)]
        assert [w.wait(timeout=30) for w in works] == \
            [i * 10 for i in range(8)]
        assert order == list(range(8))  # executed in issue order

    def test_wait_timeout_then_completes(self):
        from tpu_dist.collectives.work import _OrderedExecutor
        eng = _OrderedExecutor()
        release = threading.Event()
        w = eng.submit(lambda: (release.wait(30), "done")[1], label="slow")
        with pytest.raises(TimeoutError, match="slow"):
            w.wait(timeout=0.1)
        assert not w.is_completed()
        assert w.exception() is None      # pending, not failed
        release.set()
        assert w.wait(timeout=30) == "done"
        assert w.is_completed()

    def test_error_captured_and_reraised_at_wait(self):
        from tpu_dist.collectives.transport import PeerGoneError
        from tpu_dist.collectives.work import _OrderedExecutor
        eng = _OrderedExecutor()

        def boom():
            raise PeerGoneError(3, "injected")

        w = eng.submit(boom, label="doomed")
        # the error must not leak out of the executor thread; it is
        # captured on the handle and re-raised HERE
        with pytest.raises(PeerGoneError, match="rank 3"):
            w.wait(timeout=30)
        assert w.is_completed()
        assert isinstance(w.exception(), PeerGoneError)
        # later works on the same engine still run
        assert eng.submit(lambda: 7).wait(timeout=30) == 7

    def test_completed_work_and_wait_all(self):
        from tpu_dist.collectives.work import completed_work, wait_all
        works = [completed_work(i) for i in range(3)]
        assert all(w.is_completed() for w in works)
        assert wait_all(works, timeout=1) == [0, 1, 2]

    def test_wait_all_timeout_zero_means_poll_not_forever(self):
        # timeout=0 = "give it zero time": must raise, not hang (the
        # single-handle Work.wait(0) contract, uniformly)
        from tpu_dist.collectives.work import (_OrderedExecutor,
                                               completed_work, wait_all)
        gate = threading.Event()
        eng = _OrderedExecutor()
        pending = eng.submit(lambda: gate.wait(10), label="parked")
        with pytest.raises(TimeoutError):
            wait_all([completed_work(1), pending], timeout=0)
        assert not eng.drain(timeout=0)
        gate.set()
        pending.wait(timeout=30)

    def test_queue_wait_split_lands_on_span(self, monkeypatch):
        # the span a collective opens while executing on the engine must
        # carry queue_ns = time spent behind earlier works
        monkeypatch.setenv("TPU_DIST_OBS", "1")
        from tpu_dist.obs import recorder as obs_recorder
        obs_recorder.reset()
        from tpu_dist.obs.hooks import collective_span
        from tpu_dist.collectives.work import _OrderedExecutor
        eng = _OrderedExecutor()
        gate = threading.Event()
        eng.submit(lambda: gate.wait(30), label="blocker")
        spans = []

        def body():
            with collective_span("test_op") as ev:
                spans.append(ev)
            return True

        w = eng.submit(body, label="queued")
        time.sleep(0.25)          # let it sit queued behind the blocker
        gate.set()
        assert w.wait(timeout=30) is True
        (ev,) = spans
        assert ev.get("queue_ns", 0) >= 0.2e9, ev
        obs_recorder.reset()


# ---------------------------------------------------------------------------
# transport: dual recv, vectored send, socket tuning
# ---------------------------------------------------------------------------

@pytest.fixture
def store():
    from tpu_dist.dist.store import TCPStore
    s = TCPStore(is_master=True)
    yield s
    s.close()

@pytest.fixture
def dp_pair(store):
    from tpu_dist.collectives.transport import DataPlane
    dp0 = DataPlane(store, 0, 2)
    dp1 = DataPlane(store, 1, 2)
    yield dp0, dp1
    dp0.close()
    dp1.close()


class TestTransportAsync:
    def test_recv_array_dual_frame_wakeup(self, dp_pair):
        dp0, dp1 = dp_pair

        def late_send():
            time.sleep(0.2)
            dp0.send_array(1, "dual", np.arange(5))

        t = threading.Thread(target=late_send)
        t.start()
        t0 = time.monotonic()
        path, arr = dp1.recv_array_dual(0, "dual", timeout=30)
        dt = time.monotonic() - t0
        t.join()
        assert path == "dataplane" and arr[4] == 4
        # CV wakeup: delivery is prompt, not quantized to a poll interval
        assert dt < 5.0

    def test_recv_array_dual_alt_transport(self, dp_pair):
        dp0, dp1 = dp_pair
        hits = []

        def alt():
            hits.append(1)
            return len(hits) >= 3   # "store key" appears on the 3rd poll

        path, arr = dp1.recv_array_dual(0, "never", alt_check=alt,
                                        timeout=30)
        assert path == "alt" and arr is None
        assert len(hits) >= 3       # polled between CV waits, backed off

    def test_recv_array_dual_timeout(self, dp_pair):
        dp0, dp1 = dp_pair
        with pytest.raises(TimeoutError, match="rank 0"):
            dp1.recv_array_dual(0, "nothing", timeout=0.3)

    def test_sock_buf_negotiated_and_recorded(self, store, monkeypatch):
        # TPU_DIST_SOCK_BUF sizes both buffers; the peer-connect obs event
        # records what the kernel actually granted
        monkeypatch.setenv("TPU_DIST_SOCK_BUF", str(1 << 20))
        monkeypatch.setenv("TPU_DIST_OBS", "1")
        from tpu_dist.obs import recorder as obs_recorder
        obs_recorder.reset()
        from tpu_dist.collectives.transport import DataPlane
        dp0 = DataPlane(store, 0, 2)
        dp1 = DataPlane(store, 1, 2)
        try:
            big = np.arange(1 << 18, dtype=np.float32)
            dp0.send_array(1, "buf", big)
            got = dp1.recv_array(0, "buf", timeout=30)
            np.testing.assert_array_equal(got, big)
            rec = obs_recorder.get_recorder()
            evs = [e for e in rec.snapshot()
                   if e["kind"] == "transport" and e["op"] == "peer-connect"]
            assert evs, "no peer-connect event recorded"
            for e in evs:
                # kernels clamp/double requests; granted must be real and
                # at least the OS floor
                assert e.get("sndbuf", 0) > 0 and e.get("rcvbuf", 0) > 0, e
        finally:
            dp0.close()
            dp1.close()
            obs_recorder.reset()


# ---------------------------------------------------------------------------
# pipelined ring + bucketer (in-process thread worlds)
# ---------------------------------------------------------------------------

def _run_world(store, n, fn):
    from tpu_dist.collectives.transport import DataPlane
    dps = [DataPlane(store, r, n) for r in range(n)]
    out, errs = [None] * n, []

    def run(r):
        try:
            out[r] = fn(dps[r], r)
        except Exception as e:
            errs.append((r, e))

    threads = [threading.Thread(target=run, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for dp in dps:
        dp.close()
    assert not errs, errs
    return out


class TestPipelinedRing:
    @pytest.mark.parametrize("world", [2, 3])
    def test_tiny_subchunks_exercise_interleave(self, store, world,
                                                monkeypatch):
        # 4 KiB sub-frames over a 10007-element payload: dozens of frames
        # per ring step, so the send/fold interleave path runs for real
        monkeypatch.setenv("TPU_DIST_DP_CHUNK", "4096")
        from tpu_dist.collectives import ring
        vals = [np.random.default_rng(r).standard_normal(10007)
                .astype(np.float32) for r in range(world)]
        outs = _run_world(
            store, world,
            lambda dp, r: ring.ring_all_reduce(dp, vals[r], op="sum",
                                               tag="pipe"))
        ref = np.sum(np.stack(vals), axis=0)
        for o in outs:
            np.testing.assert_allclose(o, ref, rtol=2e-6, atol=1e-5)
        assert len({o.tobytes() for o in outs}) == 1

    def test_custom_bounds_match_default_partition(self, store):
        # explicit bounds equal to the default partition must be a no-op
        from tpu_dist.collectives import ring
        n = 3
        vals = [np.random.default_rng(10 + r).standard_normal(1001)
                .astype(np.float32) for r in range(n)]
        default = _run_world(
            store, n, lambda dp, r: ring.ring_all_reduce(dp, vals[r],
                                                         op="sum", tag="d"))
        bounds = ring._bounds(1001, n)
        custom = _run_world(
            store, n, lambda dp, r: ring.ring_all_reduce(
                dp, vals[r], op="sum", tag="c", bounds=bounds))
        for a, b in zip(default, custom):
            assert a.tobytes() == b.tobytes()

    def test_bounds_validation(self, dp_pair):
        from tpu_dist.collectives import ring
        dp0, _ = dp_pair
        with pytest.raises(ValueError, match="contiguous spans"):
            ring.ring_all_reduce(dp0, np.zeros(10, np.float32),
                                 bounds=[(0, 4), (5, 10)])


class TestBucketerBitwise:
    """THE bucketer contract: bit-identical to the unbucketed per-leaf
    ring, per element — f32 and bf16, uneven leaves, worlds 2-4, multiple
    buckets (tiny bucket_bytes), sum and avg."""

    @pytest.mark.parametrize("world", [2, 3, 4])
    @pytest.mark.parametrize("op", ["sum", "avg"])
    def test_bitwise_equal_to_per_leaf(self, store, world, op):
        import ml_dtypes
        from tpu_dist.collectives import ring
        from tpu_dist.collectives.bucketer import Bucketer

        def make_tree(r):
            g = np.random.default_rng(100 + r)
            return {
                "w1": g.standard_normal(1001).astype(np.float32),   # uneven
                "w2": g.standard_normal((7, 13)).astype(np.float32),
                "w3": g.standard_normal(509).astype(ml_dtypes.bfloat16),
                "w4": g.standard_normal(3).astype(np.float32),      # < world
                "b": np.float32(g.standard_normal()),               # scalar
            }

        trees = [make_tree(r) for r in range(world)]

        def bucketed(dp, r):
            # 4 KiB buckets force several buckets per dtype stream
            bk = Bucketer(bucket_bytes=4096, dp=dp)
            return bk.all_reduce(trees[r], op=op).wait_all(timeout=120)

        def per_leaf(dp, r):
            import jax
            leaves, td = jax.tree.flatten(trees[r])
            red = [ring.ring_all_reduce(dp, l, op=op, tag=f"pl{i}")
                   for i, l in enumerate(leaves)]
            return jax.tree.unflatten(td, red)

        got = _run_world(store, world, bucketed)
        ref = _run_world(store, world, per_leaf)
        for g_tree, r_tree in zip(got, ref):
            for k in r_tree:
                a, b = np.asarray(g_tree[k]), np.asarray(r_tree[k])
                assert a.dtype == b.dtype, (k, a.dtype, b.dtype)
                assert a.shape == b.shape, (k, a.shape, b.shape)
                assert a.tobytes() == b.tobytes(), \
                    f"world {world} op {op} leaf {k} not bitwise-equal"
        # and across ranks (the chaos-resume determinism property)
        for k in got[0]:
            assert len({np.asarray(t[k]).tobytes() for t in got}) == 1

    def test_comm_dtype_compressed_bitwise(self, store):
        # wire compression re-quantizes at the chunk owner; identical
        # chunk ownership keeps bucketed == per-leaf even then
        from tpu_dist.collectives import ring
        from tpu_dist.collectives.bucketer import Bucketer
        world = 2
        trees = [{"a": np.random.default_rng(r).standard_normal(801)
                  .astype(np.float32),
                  "b": np.random.default_rng(50 + r).standard_normal(77)
                  .astype(np.float32)} for r in range(world)]

        def bucketed(dp, r):
            bk = Bucketer(bucket_bytes=1 << 20, dp=dp,
                          comm_dtype="bfloat16")
            return bk.all_reduce(trees[r], op="sum").wait_all(timeout=60)

        def per_leaf(dp, r):
            import jax
            leaves, td = jax.tree.flatten(trees[r])
            red = [ring.ring_all_reduce(dp, l, op="sum", tag=f"cd{i}",
                                        comm_dtype="bfloat16")
                   for i, l in enumerate(leaves)]
            return jax.tree.unflatten(td, red)

        got = _run_world(store, world, bucketed)
        ref = _run_world(store, world, per_leaf)
        for g_tree, r_tree in zip(got, ref):
            for k in r_tree:
                assert np.asarray(g_tree[k]).tobytes() == \
                    np.asarray(r_tree[k]).tobytes()

    def test_issue_time_snapshot_allows_mutation_after_issue(self, store):
        # leaves are packed on the caller thread at issue: clobbering the
        # gradient arrays right after all_reduce() returns must not affect
        # the reduction (no torch-style don't-touch-until-wait hazard)
        from tpu_dist.collectives.bucketer import Bucketer
        world = 2
        base = [np.full(1001, float(r + 1), np.float32)
                for r in range(world)]

        def run(dp, r):
            t = {"g": base[r].copy()}
            w = Bucketer(bucket_bytes=1 << 20, dp=dp).all_reduce(t, op="sum")
            t["g"][:] = -999.0   # mutate AFTER issue
            return w.wait_all(timeout=60)

        outs = _run_world(store, world, run)
        for o in outs:
            np.testing.assert_array_equal(
                o["g"], np.full(1001, 3.0, np.float32))

    def test_single_process_fast_path(self):
        from tpu_dist.collectives.bucketer import Bucketer

        class _G:
            rank, num_processes = 0, 1

        tree = {"a": np.arange(5, dtype=np.float32)}
        w = Bucketer().all_reduce(tree, op="avg", group=_G())
        tree["a"][:] = -1.0   # snapshot contract holds at world 1 too
        out = w.wait_all(timeout=10)
        np.testing.assert_array_equal(out["a"],
                                      np.arange(5, dtype=np.float32))

    def test_pinned_mode_rejects_unsupported_leaves(self, dp_pair):
        from tpu_dist.collectives.bucketer import Bucketer
        dp0, _ = dp_pair
        with pytest.raises(ValueError, match="ring-only"):
            Bucketer(dp=dp0).all_reduce(
                {"s": np.array(["x", "y"])}, op="sum")


# ---------------------------------------------------------------------------
# eager async_op semantics (spawned world 2)
# ---------------------------------------------------------------------------

_WORKER_PRELUDE = textwrap.dedent("""
    import importlib, json, os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["TPU_DIST_DP_THRESHOLD"] = "0"
    import numpy as np

    rank = int(os.environ["RANK"]); world = int(os.environ["WORLD_SIZE"])
    from tpu_dist.dist.store import TCPStore
    host, _, port = os.environ["TPU_DIST_STORE_ADDR"].rpartition(":")
    store = TCPStore(host, int(port))
    rdzv = importlib.import_module("tpu_dist.dist.rendezvous")
    rdzv._store = store

    class _Group:
        def __init__(self, rank, num_processes):
            self.rank, self.num_processes = rank, num_processes
    g = _Group(rank, world)
    from tpu_dist import collectives as C
""")

_ASYNC_SEMANTICS_WORKER = _WORKER_PRELUDE + textwrap.dedent("""
    x1 = np.full(5000, float(rank + 1), np.float32)
    x2 = np.arange(3000, dtype=np.float32) * (rank + 1)

    # two async all-reduces + a broadcast issue back-to-back; results are
    # FIFO-consistent and equal to the sync path
    w1 = C.all_reduce_host(x1, group=g, op="sum", async_op=True)
    x1[:] = -777.0   # inputs are snapshotted at issue: mutation is safe
    w2 = C.all_reduce_host(x2, group=g, op="avg", async_op=True)
    bc_in = (np.full(5000, float(rank + 1), np.float32) if rank == 0
             else np.zeros(5000, np.float32))
    wb = C.broadcast_host(bc_in, group=g, src=0, async_op=True)
    # a SYNC collective issued after async work drains the queue first:
    # by the time it runs, w1/w2/wb must already be complete
    sync = C.all_gather_host(np.float32(rank), group=g)
    assert w1.is_completed() and w2.is_completed() and wb.is_completed(), \\
        "sync collective overtook queued async work"

    total = sum(r + 1 for r in range(world))
    np.testing.assert_allclose(w1.wait(timeout=60),
                               np.full(5000, total, np.float32))
    np.testing.assert_allclose(
        w2.wait(timeout=60),
        np.arange(3000, dtype=np.float32) * (total / world))
    np.testing.assert_allclose(wb.wait(timeout=60),
                               np.full(5000, 1.0, np.float32))
    assert sync.shape == (world,)

    # async send/recv (isend/irecv flavor)
    if rank == 0:
        hs = C.send(np.arange(2000, dtype=np.float32), dst=1, group=g,
                    async_op=True)
        assert hs.wait(timeout=60) is None
    else:
        hr = C.recv(src=0, group=g, async_op=True)
        got = hr.wait(timeout=60)
        np.testing.assert_array_equal(got,
                                      np.arange(2000, dtype=np.float32))

    store.barrier(world, tag="done")
    with open(sys.argv[1] + f"/result{rank}.json", "w") as f:
        json.dump({"ok": True}, f)
    store.close()
""")

_ASYNC_PEER_DEATH_WORKER = _WORKER_PRELUDE + textwrap.dedent("""
    if rank == 1:
        # participate in ONE collective so rank 0's plane knows us, then
        # die with the second collective owed
        C.all_reduce_host(np.full(4096, 1.0, np.float32), group=g, op="sum")
        store.close()
        os._exit(0)

    C.all_reduce_host(np.full(4096, 1.0, np.float32), group=g, op="sum")
    w = C.all_reduce_host(np.full(4096, 2.0, np.float32), group=g,
                          op="sum", async_op=True)
    # the error is captured while the work executes; wait() re-raises it
    # on THIS thread, naming the dead peer
    from tpu_dist.collectives.transport import PeerGoneError
    try:
        w.wait(timeout=120)
        raise SystemExit("expected PeerGoneError at wait()")
    except PeerGoneError as e:
        assert "rank 1" in str(e), str(e)
        assert isinstance(w.exception(), PeerGoneError)
    with open(sys.argv[1] + "/result0.json", "w") as f:
        json.dump({"ok": True, "error": "PeerGoneError"}, f)
    store.close()
""")


def _spawn_world(tmp_path, source, world, timeout=180):
    from tpu_dist.dist.store import TCPStore
    script = tmp_path / "worker.py"
    script.write_text(source)
    server = TCPStore(is_master=True)
    env = dict(os.environ,
               PYTHONPATH=_REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                              ""),
               JAX_PLATFORMS="cpu",
               TPU_DIST_STORE_ADDR=f"127.0.0.1:{server.port}",
               WORLD_SIZE=str(world))
    env.pop("TPU_DIST_RESTART_COUNT", None)
    env.pop("TPU_DIST_DP_THRESHOLD", None)
    try:
        procs = [subprocess.Popen(
            [sys.executable, str(script), str(tmp_path)],
            env=dict(env, RANK=str(r)), cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for r in range(world)]
        outs = [p.communicate(timeout=timeout) for p in procs]
        rcs = [p.returncode for p in procs]
    finally:
        server.close()
    assert rcs == [0] * world, "\n\n".join(
        f"rank {r} rc={rc}\nstdout:\n{o}\nstderr:\n{e}"
        for r, (rc, (o, e)) in enumerate(zip(rcs, outs)) if rc != 0)
    return [json.loads((tmp_path / f"result{r}.json").read_text())
            if (tmp_path / f"result{r}.json").exists() else None
            for r in range(world)]


def test_eager_async_op_semantics(tmp_path):
    """async_op=True returns Work futures equal to the sync results, FIFO
    ordering holds, a sync collective drains queued async work, and async
    send/recv round-trip."""
    res = _spawn_world(tmp_path, _ASYNC_SEMANTICS_WORKER, 2)
    assert all(r == {"ok": True} for r in res)


def test_async_error_captured_at_issue_raised_at_wait(tmp_path):
    """A peer dying mid-async-collective surfaces as PeerGoneError at
    wait(), naming the dead rank — not an unraisable error on the engine
    thread."""
    res = _spawn_world(tmp_path, _ASYNC_PEER_DEATH_WORKER, 2)
    assert res[0] == {"ok": True, "error": "PeerGoneError"}


# ---------------------------------------------------------------------------
# the overlap benchmark's smoke mode IS a tier-1 test (ISSUE 5 CI gate)
# ---------------------------------------------------------------------------

def test_bench_overlap_smoke():
    env = dict(os.environ,
               PYTHONPATH=_REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                              ""),
               JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "benchmarks.bench_overlap", "--smoke"],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    rows = [json.loads(line) for line in r.stdout.strip().splitlines()]
    by_mode = {row["mode"]: row["value"] for row in rows
               if row.get("metric") == "grad_sync"}
    for mode in ("per_leaf_sync", "per_leaf_async", "tree_sync",
                 "bucketed_async"):
        assert by_mode.get(mode, 0) > 0, by_mode
