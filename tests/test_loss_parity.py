"""Loss-curve parity: DDP-8-replica training == single-device training on
the gathered batches over many steps — the reference's only correctness
oracle (eyeballed loss curves, SURVEY.md §4), automated."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tpu_dist.dist as dist
from tpu_dist import nn, optim
from tpu_dist.data import (ArrayImageDataset, DataLoader, DeviceLoader,
                           DistributedSampler)
from tpu_dist.data.datasets import synthetic_mnist_arrays
from tpu_dist.models import ConvNet
from tpu_dist.parallel import DDP

pytestmark = pytest.mark.slow


def test_mnist_curve_parity():
    if dist.is_initialized():
        dist.destroy_process_group()
    pg = dist.init_process_group()
    try:
        x, y = synthetic_mnist_arrays(True, n=2048)
        ds = ArrayImageDataset(x, y)
        model = ConvNet()
        loss_fn = nn.CrossEntropyLoss()

        # --- DDP run: 8 replicas, global batch 128 ---
        ddp = DDP(ConvNet(), optimizer=optim.SGD(lr=0.05),
                  loss_fn=loss_fn, group=pg, donate=False)
        state = ddp.init(seed=0)
        loader = DeviceLoader(DataLoader(ds, batch_size=128, drop_last=True),
                              group=pg)
        ddp_curve = []
        for xb, yb in loader:
            state, m = ddp.train_step(state, xb, yb)
            ddp_curve.append(float(m["loss"]))

        # --- single-device run: same batches ---
        params = model.init(jax.random.key(0))
        opt = optim.SGD(lr=0.05)
        opt_state = opt.init(params)

        @jax.jit
        def step(p, s, xb, yb):
            def l(pp):
                return loss_fn(model.apply(pp, xb), yb)
            loss, g = jax.value_and_grad(l)(p)
            p, s = opt.update(g, s, p)
            return p, s, loss

        single_curve = []
        for xb, yb in DataLoader(ds, batch_size=128, drop_last=True):
            params, opt_state, loss = step(params, opt_state,
                                           jnp.asarray(xb), jnp.asarray(yb))
            single_curve.append(float(loss))

        assert len(ddp_curve) == len(single_curve) == 16
        np.testing.assert_allclose(ddp_curve, single_curve,
                                   rtol=5e-3, atol=5e-4)
        # and training must actually progress
        assert ddp_curve[-1] < ddp_curve[0]
    finally:
        dist.destroy_process_group()

