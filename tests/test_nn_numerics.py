"""Numerical parity of tpu_dist.nn ops/layers against torch CPU.

The reference's numerical substrate is torch's ATen kernels
(/root/reference/mpspawn_dist.py:11-43 ConvNet ops); these tests pin our
XLA-lowered ops to the same math.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import jax
import jax.numpy as jnp

from tpu_dist import nn
from tpu_dist.nn import functional as F

# compile-heavy file: excluded from the fast tier (`pytest -m "not slow"`)
pytestmark = pytest.mark.slow


def to_nhwc(x_nchw: np.ndarray) -> np.ndarray:
    return np.transpose(x_nchw, (0, 2, 3, 1))


def to_nchw(x_nhwc: np.ndarray) -> np.ndarray:
    return np.transpose(x_nhwc, (0, 3, 1, 2))


def hwio_from_oihw(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (2, 3, 1, 0))


@pytest.mark.parametrize("stride,padding,kernel", [(1, 1, 5), (1, 0, 3), (2, 2, 3)])
def test_conv2d_matches_torch(rng, stride, padding, kernel):
    x = rng.standard_normal((4, 1 if kernel == 5 else 8, 14, 14)).astype(np.float32)
    cin = x.shape[1]
    w = rng.standard_normal((6, cin, kernel, kernel)).astype(np.float32)
    b = rng.standard_normal((6,)).astype(np.float32)

    ref = tF.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                    stride=stride, padding=padding).numpy()
    out = F.conv2d(jnp.asarray(to_nhwc(x)), jnp.asarray(hwio_from_oihw(w)),
                   jnp.asarray(b), stride=stride, padding=padding)
    np.testing.assert_allclose(to_nchw(np.asarray(out)), ref, atol=1e-4)


@pytest.mark.parametrize("kernel,stride", [(2, 2), (2, 1), (3, 2)])
def test_max_pool_matches_torch(rng, kernel, stride):
    x = rng.standard_normal((2, 5, 13, 13)).astype(np.float32)
    ref = tF.max_pool2d(torch.tensor(x), kernel, stride).numpy()
    out = F.max_pool2d(jnp.asarray(to_nhwc(x)), kernel, stride)
    np.testing.assert_allclose(to_nchw(np.asarray(out)), ref, atol=1e-6)


def test_cross_entropy_matches_torch(rng):
    logits = rng.standard_normal((16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, size=(16,))
    ref = tF.cross_entropy(torch.tensor(logits), torch.tensor(labels)).item()
    out = float(F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    assert abs(out - ref) < 1e-5


def test_linear_matches_torch(rng):
    x = rng.standard_normal((3, 7)).astype(np.float32)
    w = rng.standard_normal((5, 7)).astype(np.float32)  # torch (out, in)
    b = rng.standard_normal((5,)).astype(np.float32)
    ref = tF.linear(torch.tensor(x), torch.tensor(w), torch.tensor(b)).numpy()
    out = F.linear(jnp.asarray(x), jnp.asarray(w.T), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


def test_batchnorm_train_and_eval_match_torch(rng):
    x = rng.standard_normal((4, 3, 6, 6)).astype(np.float32)
    tbn = torch.nn.BatchNorm2d(3)
    tbn.train()
    ref_train = tbn(torch.tensor(x)).detach().numpy()
    run_mean = tbn.running_mean.numpy().copy()
    run_var = tbn.running_var.numpy().copy()

    bn = nn.BatchNorm2d(3)
    params = bn.init(jax.random.key(0))  # weight=1, bias=0 matches torch init
    state = bn.init_state()
    out, new_state = bn.apply(params, jnp.asarray(to_nhwc(x)), state=state,
                              training=True)
    np.testing.assert_allclose(to_nchw(np.asarray(out)), ref_train, atol=1e-4)
    np.testing.assert_allclose(np.asarray(new_state[""]["mean"]), run_mean,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_state[""]["var"]), run_var,
                               atol=1e-4)

    tbn.eval()
    x2 = rng.standard_normal((4, 3, 6, 6)).astype(np.float32)
    ref_eval = tbn(torch.tensor(x2)).detach().numpy()
    out2, _ = bn.apply(params, jnp.asarray(to_nhwc(x2)), state=new_state,
                       training=False)
    np.testing.assert_allclose(to_nchw(np.asarray(out2)), ref_eval, atol=1e-4)


def test_dropout_train_eval():
    x = jnp.ones((1000,))
    drop = nn.Dropout(0.5)
    y = drop.apply({}, x, training=True, rng=jax.random.key(0))
    kept = float((y > 0).mean())
    assert 0.4 < kept < 0.6
    np.testing.assert_allclose(np.asarray(y[y > 0]), 2.0)  # inverted scaling
    y_eval = drop.apply({}, x, training=False)
    np.testing.assert_allclose(np.asarray(y_eval), np.asarray(x))


def test_module_requires_apply():
    lin = nn.Linear(3, 2)
    with pytest.raises(RuntimeError):
        lin(jnp.ones((1, 3)))


def test_avg_pool_padded_matches_torch(rng):
    x = rng.standard_normal((1, 3, 4, 4)).astype(np.float32)
    ref = tF.avg_pool2d(torch.tensor(x), 2, 2, padding=1).numpy()
    out = F.avg_pool2d(jnp.asarray(to_nhwc(x)), 2, 2, padding=1)
    np.testing.assert_allclose(to_nchw(np.asarray(out)), ref, atol=1e-6)


def test_weight_tying_shares_params():
    lin = nn.Linear(4, 4)

    class Tied(nn.Module):
        def __init__(self):
            super().__init__()
            self.a = lin
            self.b = lin

        def forward(self, x):
            return self.b(self.a(x))

    model = Tied()
    params = model.init(jax.random.key(0))
    assert list(params) == ["a"]  # one shared parameter set
    out = model.apply(params, jnp.ones((1, 4)))
    assert out.shape == (1, 4)


def test_sequential_is_iterable():
    seq = nn.Sequential(nn.ReLU(), nn.ReLU())
    assert len(list(iter(seq))) == 2
    with pytest.raises(IndexError):
        seq[5]


def test_adaptive_avg_pool_general_bins_match_torch(rng):
    """Non-divisible and output>input shapes follow torch's bin rule
    (floor(i*H/out) .. ceil((i+1)*H/out)) — the VGG-on-CIFAR 1x1 -> 7x7
    case included."""
    from tpu_dist.nn.layers import AdaptiveAvgPool2d

    for (h, w), (oh, ow) in [((1, 1), (7, 7)), ((5, 7), (3, 2)),
                             ((10, 3), (7, 7)), ((6, 6), (4, 4))]:
        x = rng.standard_normal((2, h, w, 3)).astype(np.float32)
        got = np.asarray(AdaptiveAvgPool2d((oh, ow)).apply({}, x))
        want = torch.nn.functional.adaptive_avg_pool2d(
            torch.tensor(x).permute(0, 3, 1, 2),
            (oh, ow)).permute(0, 2, 3, 1).numpy()
        np.testing.assert_allclose(got, want, atol=1e-6)


class TestCrossEntropyOptions:
    """label_smoothing / ignore_index / weight vs torch, all combos."""

    @pytest.mark.parametrize("smoothing,weighted,ignore", [
        (0.0, False, False), (0.1, False, False), (0.0, True, False),
        (0.0, False, True), (0.1, True, False), (0.1, True, True),
        (0.0, True, True),
    ])
    def test_matches_torch(self, rng, smoothing, weighted, ignore):
        import torch
        from tpu_dist import nn as tnn

        logits = rng.standard_normal((12, 7)).astype(np.float32)
        labels = rng.integers(0, 7, 12).astype(np.int64)
        if ignore:
            labels[::3] = -100
        w = (rng.uniform(0.5, 2.0, 7).astype(np.float32) if weighted
             else None)

        for reduction in ("mean", "sum", "none"):
            ours = tnn.CrossEntropyLoss(
                reduction=reduction, label_smoothing=smoothing,
                weight=None if w is None else jnp.asarray(w))
            got = ours(jnp.asarray(logits), jnp.asarray(labels))
            tl = torch.nn.CrossEntropyLoss(
                reduction=reduction, label_smoothing=smoothing,
                weight=None if w is None else torch.tensor(w))
            want = tl(torch.tensor(logits), torch.tensor(labels))
            np.testing.assert_allclose(np.asarray(got),
                                       want.detach().numpy(), rtol=2e-5,
                                       atol=1e-6,
                                       err_msg=f"{reduction} s={smoothing} "
                                               f"w={weighted} ig={ignore}")

    def test_all_ignored_mean_is_finite(self):
        from tpu_dist import nn as tnn
        loss = tnn.CrossEntropyLoss()(jnp.zeros((3, 4)),
                                      jnp.full(3, -100, jnp.int32))
        assert float(loss) == 0.0  # guarded denominator, not NaN

    def test_fused_rejects_options(self):
        from tpu_dist import nn as tnn
        with pytest.raises(ValueError, match="fused"):
            tnn.CrossEntropyLoss(fused=True, label_smoothing=0.1)

    def test_fused_ignore_index_matches_dense(self, rng):
        """The fused path masks ignore_index outside the kernel — same
        numbers as the dense path (pad rows excluded from the mean)."""
        from tpu_dist import nn as tnn
        logits = jnp.asarray(rng.standard_normal((16, 32)).astype(np.float32))
        labels = np.asarray(rng.integers(0, 32, 16))
        labels[::4] = -100
        labels = jnp.asarray(labels)
        for reduction in ("mean", "sum", "none"):
            fused = tnn.CrossEntropyLoss(reduction=reduction, fused=True)
            dense = tnn.CrossEntropyLoss(reduction=reduction)
            np.testing.assert_allclose(
                np.asarray(fused(logits, labels)),
                np.asarray(dense(logits, labels)), rtol=1e-5, atol=1e-6)
